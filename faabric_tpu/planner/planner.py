"""The cluster-singleton control plane.

Reference analog: src/planner/Planner.cpp (1415 lines), in particular
callBatch (:807-1292), dispatchSchedulingDecision (:1293-1397),
registerHost (:295-365), setMessageResult (:394-540), host expiry
(:383-392).

State held: host map (slots, chips, MPI port pool, register timestamp),
in-flight apps (request + decision), app results, result waiters (hosts to
push results to), preloaded decisions, frozen (evicted) apps, and the
migration counter.

TPU-first deltas from the reference:
- Slots are execution slots as in the reference, but every placement also
  pins a **device id** — the chip on the chosen host — picked least-loaded
  from the host's chip inventory; MPI/collective groups read it from the
  decision to build their ``jax.sharding.Mesh``.
- MPI ports come from a per-host pool as in the reference
  (Planner.cpp:79-120); on TPU they parameterise the host-side PTP data
  plane, while the device data plane rides ICI via XLA collectives.

Like the reference (Planner.cpp:814), call_batch serialises on one lock —
scheduling throughput is not the bottleneck; slot accounting correctness is.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Optional

from faabric_tpu.batch_scheduler import (
    DecisionType,
    HostState,
    SchedulingDecision,
    get_batch_scheduler,
    is_sentinel_decision,
)
from faabric_tpu.batch_scheduler.decision import MUST_FREEZE, NOT_ENOUGH_SLOTS
from faabric_tpu.proto import (
    BatchExecuteRequest,
    BatchExecuteRequestStatus,
    BatchExecuteType,
    Message,
    ReturnValue,
    update_batch_exec_group_id,
)
from faabric_tpu.faults import DROP, fault_point, faults_enabled
from faabric_tpu.telemetry import (
    flight_dump,
    flight_record,
    get_lifecycle,
    get_metrics,
    span,
)
from faabric_tpu.telemetry.lifecycle import (
    PHASE_ADMIT,
    PHASE_DISPATCH,
    PHASE_JOURNAL,
    PHASE_RECORDED,
    PHASE_REQUEUE,
    PHASE_SCHED,
)
from faabric_tpu.transport.common import MPI_BASE_PORT, MPI_PORTS_PER_HOST
from faabric_tpu.util.config import get_system_config
from faabric_tpu.util.gids import generate_gid
from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

_FAULTS = faults_enabled()
_FP_DISPATCH = fault_point("planner.dispatch")

# Invocation lifecycle ledger (ISSUE 14): schedule/journal/dispatch/
# requeue/record stamps on the messages themselves (shared no-op
# singleton when FAABRIC_METRICS=0)
_LC = get_lifecycle()

_metrics = get_metrics()
_SCHEDULE_SECONDS = _metrics.histogram(
    "faabric_planner_schedule_seconds",
    "End-to-end call_batch latency (decision + mappings + dispatch)")
_DISPATCH_SECONDS = _metrics.histogram(
    "faabric_planner_dispatch_seconds",
    "Per-decision worker dispatch latency (network, post-lock)")
_IN_FLIGHT_APPS = _metrics.gauge(
    "faabric_planner_in_flight_apps",
    "Apps currently holding slots on the planner")
_RESULTS_TOTAL = _metrics.counter(
    "faabric_planner_results_total",
    "Message results recorded by the planner")
_RESULT_ROUNDTRIP = _metrics.histogram(
    "faabric_planner_result_roundtrip_seconds",
    "Message creation to result recorded at the planner (wall clocks of "
    "the submitting host and the planner: cross-machine skew shifts it)")
_REQUEUES_TOTAL = _metrics.counter(
    "faabric_planner_requeues_total",
    "Recovery requeues performed (one per affected app per failure)")
_REQUEUED_MESSAGES = _metrics.counter(
    "faabric_planner_requeued_messages_total",
    "Messages moved to surviving hosts by recovery requeues")
_RETRY_EXHAUSTED = _metrics.counter(
    "faabric_planner_retry_exhausted_total",
    "Messages terminally failed after the requeue budget ran out")
_RECOVERY_SECONDS = _metrics.histogram(
    "faabric_planner_recovery_seconds",
    "Failure detection to requeued messages re-dispatched (includes the "
    "backoff delay)")
_JOURNAL_REPLAY_SECONDS = _metrics.histogram(
    "faabric_planner_journal_replay_seconds",
    "Wall time to rebuild planner state from the write-ahead journal "
    "at restart (snapshot load + record application)")
_RECONCILED_MESSAGES = _metrics.counter(
    "faabric_planner_journal_reconciled_messages_total",
    "Replayed in-flight messages handed to requeue recovery because "
    "their host never re-registered within the reconcile grace window")


class PlannerHost:
    """Planner-side record for one registered worker host."""

    def __init__(self, ip: str, slots: int, n_devices: int = 0) -> None:
        self.state = HostState(ip=ip, slots=slots, n_devices=n_devices)
        self.register_ts = time.monotonic()
        self.used_mpi_ports: set[int] = set()
        # ranks pinned per chip — placements pick the least-loaded chip
        self.device_load: list[int] = [0] * max(0, n_devices)

    def claim_mpi_port(self) -> int:
        for port in range(MPI_BASE_PORT, MPI_BASE_PORT + MPI_PORTS_PER_HOST):
            if port not in self.used_mpi_ports:
                self.used_mpi_ports.add(port)
                return port
        raise RuntimeError(f"Host {self.state.ip} exhausted its MPI port pool")

    def release_mpi_port(self, port: int) -> None:
        self.used_mpi_ports.discard(port)

    def claim_device(self) -> int:
        if not self.device_load:
            return -1
        dev = self.device_load.index(min(self.device_load))
        self.device_load[dev] += 1
        return dev

    def release_device(self, dev: int) -> None:
        if 0 <= dev < len(self.device_load) and self.device_load[dev] > 0:
            self.device_load[dev] -= 1


class Planner:
    # Concurrency contract (tools/concheck.py, docs/static_analysis.md):
    # every listed attribute may only be touched inside `with
    # self._lock`. One RLock guards the whole control-plane state —
    # scheduling correctness depends on decisions/claims/results
    # mutating atomically, and the hot path (one dict hit per RPC) does
    # not contend enough to shard it. NOT listed: boot_id (immutable
    # after __init__), _telemetry_scrapes (GIL-atomic setdefault/pop by
    # design), _clients/_snapshot_clients/_journal/snapshot_registry/
    # ingress (internally synchronized), _journal_replay_stats/
    # _reconcile_stats (write-once diagnostics), _perf_agg_stats
    # (GIL-atomic whole-dict swap), _reconcile_timer
    # (start/stop sequenced by recovery).
    GUARDS = {
        "_hosts": "_lock",
        "_in_flight": "_lock",
        "_results": "_lock",
        "_expected": "_lock",
        "_next_idx": "_lock",
        "_completed_order": "_lock",
        "_waiters": "_lock",
        "_requeue_attempts": "_lock",
        "_preloaded": "_lock",
        "_evicted": "_lock",
        "_next_evicted_ips": "_lock",
        "_group_hosts": "_lock",
        "_num_migrations": "_lock",
        "_state_masters": "_lock",
        "_state_backups": "_lock",
        "_state_epochs": "_lock",
        "_device_plane": "_lock",
        "_journal_last_hosts": "_lock",
        "_results_count": "_lock",
        "_results_failed": "_lock",
    }

    def __init__(self) -> None:
        # Fresh per process incarnation, NEVER journaled: keep-alive
        # responses carry it so a client can tell "the planner
        # restarted and journal replay re-registered me (known stays
        # True, but the in-memory waiter map and any kernel-buffered
        # result writes died)" apart from an ordinary tick.
        self.boot_id = uuid.uuid4().hex
        self._lock = threading.RLock()
        # host ip → live scrape thread (collect_telemetry); setdefault/pop
        # on the GIL-atomic dict bound in-flight scrapes to one per host
        self._telemetry_scrapes: dict[str, threading.Thread] = {}
        self._hosts: dict[str, PlannerHost] = {}
        # app_id → (req, decision)
        self._in_flight: dict[int, tuple[BatchExecuteRequest, SchedulingDecision]] = {}
        # app_id → {msg_id: result Message}
        self._results: dict[int, dict[int, Message]] = {}
        # app_id → expected message count (survives in_flight cleanup)
        self._expected: dict[int, int] = {}
        # app_id → next unassigned app/group index (monotonic — never
        # derived from remaining-message counts, which shrink as results
        # complete)
        self._next_idx: dict[int, int] = {}
        # Completed apps in completion order, for bounded result retention
        self._completed_order: list[int] = []
        # Results recorded this incarnation (monotonic; /healthz
        # resultsTotal — what a high-QPS driver polls for completion)
        # and how many of them were FAILED (a driver counting
        # completions must be able to tell success from shed/failure)
        self._results_count = 0
        self._results_failed = 0
        # (app_id, msg_id) → hosts to push the result to
        self._waiters: dict[tuple[int, int], set[str]] = {}
        # app_id → recovery requeues already spent (bounded by
        # conf.planner_max_requeues; cleared when the app completes)
        self._requeue_attempts: dict[int, int] = {}
        # app_id → decision preloaded via REST/tests
        self._preloaded: dict[int, SchedulingDecision] = {}
        # app_id → frozen request (spot eviction)
        self._evicted: dict[int, BatchExecuteRequest] = {}
        self._next_evicted_ips: set[str] = set()
        # app_id → (every group_id the app ever used — migration mints new
        # ones — and all hosts ever involved) for group cleanup
        self._group_hosts: dict[int, tuple[set[int], set[str]]] = {}
        self._num_migrations = 0

        from faabric_tpu.scheduler.function_call import FunctionCallClient
        from faabric_tpu.transport.client_pool import ClientPool

        self._clients = ClientPool(FunctionCallClient)

        # Snapshots parked on the planner for THREADS distribution and
        # frozen apps (reference planner-held SnapshotRegistry)
        from faabric_tpu.snapshot.registry import SnapshotRegistry
        from faabric_tpu.snapshot.remote import SnapshotClient

        self.snapshot_registry = SnapshotRegistry()
        self._snapshot_clients = ClientPool(SnapshotClient)

        # State-KV master election: "user/key" → owning host. The
        # reference elects masters through Redis (InMemoryStateRegistry
        # getMasterIP(claim)); here the planner IS the cluster metadata
        # service, so a claim is one RPC with no external dependency.
        self._state_masters: dict[str, str] = {}
        # Crash tolerance (ISSUE 19): per-key backup host (consistent-
        # hash placed, always != master) and fencing epoch. Epochs are
        # NEVER deleted on drop — only reset() clears them — so a
        # re-claimed key always gets a strictly higher epoch and a
        # revived stale master can never ack under its old one.
        self._state_backups: dict[str, str] = {}
        self._state_epochs: dict[str, int] = {}

        # Multi-process device plane (parallel/distributed.py): workers
        # join at boot; the planner assigns process ids in join order
        # and elects the first joiner's host as jax.distributed
        # coordinator. Worker-lifetime, not per-app — the TPU analog of
        # claiming a pod slice.
        self._device_plane: dict = {"roster": [], "size": 0, "port": 0}

        # Crash safety (ISSUE 4): every durable mutation below appends
        # to the write-ahead journal (planner/journal.py; the shared
        # no-op when FAABRIC_PLANNER_JOURNAL_DIR is unset), and a
        # restarted planner replays itself back before serving.
        from faabric_tpu.planner.journal import open_planner_journal

        self._journal = open_planner_journal()
        # Replay-only view of the host registry at crash time: hosts
        # are NEVER resurrected as live (their keep-alive clock died
        # with the old process) — they re-register via the existing
        # known:false rejoin path, and _reconcile_after_restart
        # requeues what belonged to hosts that never come back.
        self._journal_last_hosts: set[str] = set()
        self._journal_replay_stats: Optional[dict] = None
        self._reconcile_stats: Optional[dict] = None
        # Last /perf aggregation summary (GIL-atomic whole-dict swap,
        # same discipline as the write-once diagnostics above): the
        # healthz perf block and the doctor read staleness off it
        self._perf_agg_stats: Optional[dict] = None
        self._reconcile_timer: Optional[threading.Timer] = None
        if self._journal.enabled:
            self._recover_from_journal()

        # High-QPS invocation ingress (ISSUE 8): admission control +
        # batched scheduling ticks between the endpoints and call_batch.
        # Internally synchronized; its tick thread starts lazily on the
        # first batched submission and is stopped by PlannerServer.
        from faabric_tpu.ingress import IngressCoordinator

        self.ingress = IngressCoordinator(self)

    # ------------------------------------------------------------------
    # Host membership (reference Planner.cpp:267-392)
    # ------------------------------------------------------------------
    def register_host(self, ip: str, slots: int, n_devices: int = 0,
                      overwrite: bool = False) -> float:
        conf = get_system_config()
        fresh = False
        with self._lock:
            existing = self._hosts.get(ip)
            if existing is None or overwrite:
                self._hosts[ip] = PlannerHost(ip, slots, n_devices)
                # Every overwrite registration is a worker BOOT — even if
                # the previous entry already expired off the registry,
                # a pooled connection to the dead incarnation may remain
                fresh = overwrite
                # A brand-new PlannerHost starts with zero used slots,
                # but in-flight decisions may still pin rows to this ip
                # (planner restart replay; rejoin racing a recovery
                # pass) — re-apply those claims or the host would
                # oversubscribe until the app drains
                self._reclaim_host_rows_locked(ip)
                if self._journal.enabled:
                    self._journal_append("host_register", ip=ip,
                                         slots=slots, n_devices=n_devices)
                logger.debug("Planner registered host %s (slots=%d chips=%d)",
                             ip, slots, n_devices)
            else:
                # Keep-alive: refresh timestamp (and allow growing slots)
                existing.register_ts = time.monotonic()
                existing.state.slots = slots
                if n_devices != len(existing.device_load):
                    existing.device_load = [0] * max(0, n_devices)
                    existing.state.n_devices = n_devices
        if fresh:
            # A RE-registration with overwrite is a worker process boot:
            # any pooled connection to the previous incarnation is dead,
            # and an async dispatch onto it can strand silently while
            # the new worker's keep-alives keep the host looking healthy
            self._clients.drop(ip)
            self._snapshot_clients.drop(ip)
        return conf.planner_host_timeout

    def is_host_registered(self, ip: str) -> bool:
        """Whether the host currently exists in the registry — the bit a
        keep-alive response carries so an expired-but-alive worker can
        detect it fell out and rejoin with overwrite=True."""
        with self._lock:
            return ip in self._hosts

    def remove_host(self, ip: str) -> None:
        with self._lock:
            existed = self._hosts.pop(ip, None) is not None
            # A deregistered host cannot serve state reads: drop its
            # masterships so the next claim re-elects a live host
            # (satellite fix — previously the key resolved to a corpse
            # forever)
            self._drop_state_masters_for_locked({ip})
            if existed and self._journal.enabled:
                self._journal_append("host_remove", ip=ip)

    def _drop_state_masters_for_locked(self, ips: set[str]) -> None:
        """Fail over (or drop) every state-master entry owned by ``ips``
        (called under the planner lock on host death/removal/expiry).

        ISSUE 19: a dead master whose backup is still live is PROMOTED —
        epoch bumped, transition journalled durably, a new backup
        elected — instead of dropped; only when master AND backup are
        both gone does the entry drop (honest data loss, see
        docs/fault_tolerance.md). A dead backup under a live master just
        gets a replacement elected (no epoch bump: ownership did not
        change). Promotion RPCs are dispatched on a daemon thread — no
        network I/O ever happens under the planner lock."""
        promoted: list[tuple[str, str, str, int]] = []
        dropped: list[str] = []
        for full, master in list(self._state_masters.items()):
            backup = self._state_backups.get(full, "")
            if master in ips:
                if backup and backup not in ips and backup in self._hosts:
                    epoch = self._state_epochs.get(full, 0) + 1
                    new_backup = self._elect_backup_locked(
                        full, {backup} | set(ips))
                    self._state_masters[full] = backup
                    self._state_backups[full] = new_backup
                    self._state_epochs[full] = epoch
                    if self._journal.enabled:
                        self._journal_append("state_failover", key=full,
                                             host=backup, backup=new_backup,
                                             epoch=epoch)
                    flight_record("state_failover", key=full,
                                  old_master=master, new_master=backup,
                                  backup=new_backup, epoch=epoch)
                    promoted.append((full, backup, new_backup, epoch))
                else:
                    del self._state_masters[full]
                    self._state_backups.pop(full, None)
                    if self._journal.enabled:
                        self._journal_append("state_drop", key=full)
                    dropped.append(full)
            elif backup and backup in ips:
                new_backup = self._elect_backup_locked(
                    full, {master} | set(ips))
                self._state_backups[full] = new_backup
                if self._journal.enabled:
                    self._journal_append("state_backup", key=full,
                                         backup=new_backup)
        if dropped:
            logger.warning("Dropped %d state mastership(s) of dead host(s) "
                           "%s (no live backup)", len(dropped), sorted(ips))
        if promoted:
            logger.warning(
                "Failing over %d state mastership(s) from dead host(s) %s",
                len(promoted), sorted(ips))
            self._dispatch_state_promotions(promoted)

    def _elect_backup_locked(self, full: str, exclude: set[str]) -> str:
        """Consistent-hash backup election among live registered hosts
        (empty string when replication is off or no eligible host)."""
        if get_system_config().state_replicas <= 0:
            return ""
        live = [h for h in self._hosts if h not in exclude]
        if not live:
            return ""
        from faabric_tpu.state.placement import place_backup

        return place_backup(full, live)

    def _dispatch_state_promotions(
            self, promoted: list[tuple[str, str, str, int]]) -> None:
        threading.Thread(
            target=self._notify_state_promotions, args=(list(promoted),),
            name="planner/state-promote", daemon=True).start()

    def _notify_state_promotions(
            self, promoted: list[tuple[str, str, str, int]]) -> None:
        """Tell each promoted backup to convert its replica into the
        master copy. Best-effort: a lost notification is covered by
        self-promotion — the first fenced client op carrying the new
        epoch triggers the same conversion on the backup host."""
        from faabric_tpu.state.remote import StateClient

        for full, master, backup, epoch in promoted:
            user, _, key = full.partition("/")
            try:
                client = StateClient(master)
                try:
                    ok = client.promote(user, key, epoch, backup)
                finally:
                    client.close()
            except Exception as e:  # noqa: BLE001 — best-effort notify
                logger.warning(
                    "State promotion notify %s -> %s failed: %s (the new "
                    "master self-promotes on its first fenced op)",
                    full, master, e)
                continue
            if not ok:
                logger.warning(
                    "Host %s holds no replica of %s; dropping the "
                    "mastership so the next claim re-elects", master, full)
                self._drop_failed_promotion(full, epoch)

    def _drop_failed_promotion(self, full: str, epoch: int) -> None:
        """A promoted host reported no replica: drop the entry (keeping
        the epoch) unless a newer transition already superseded it."""
        with self._lock:
            if self._state_epochs.get(full, 0) != epoch:
                return
            if self._state_masters.pop(full, None) is not None:
                self._state_backups.pop(full, None)
                if self._journal.enabled:
                    self._journal_append("state_drop", key=full)

    def expire_hosts(self) -> None:
        conf = get_system_config()
        now = time.monotonic()
        doomed: dict[int, list[Message]] = {}
        with self._lock:
            stale = [ip for ip, h in self._hosts.items()
                     if now - h.register_ts > conf.planner_host_timeout]
            for ip in stale:
                logger.warning("Expiring host %s (no keep-alive)", ip)
                flight_record("host_expired", host=ip)
                del self._hosts[ip]
                if self._journal.enabled:
                    self._journal_append("host_expired", ip=ip)
            if stale:
                self._drop_state_masters_for_locked(set(stale))
                # A dead worker cannot report results: recover its
                # in-flight messages so batch waiters unblock instead of
                # hanging forever (dispatch is async fire-and-forget — a
                # write onto a pooled connection to a just-killed
                # process can "succeed" into the kernel buffer, so
                # dispatch-time error handling alone cannot catch this)
                stale_set = set(stale)
                for app_id, (req, decision) in self._in_flight.items():
                    for i, h in enumerate(decision.hosts):
                        if h in stale_set:
                            mid = decision.message_ids[i]
                            doomed.setdefault(app_id, []).extend(
                                m for m in req.messages if m.id == mid)
        if doomed:
            # expire_hosts runs under callers' locks (_policy_host_map_locked);
            # recovery re-enters the RLock and pushes over the network —
            # defer to a thread so no network I/O ever happens under the
            # planner lock. One thread per affected app: their backoffs
            # must not serialize behind each other.
            for app_id, msgs in doomed.items():
                threading.Thread(
                    target=self._recover_messages,
                    args=(app_id, msgs, b"Host expired"),
                    name=f"planner/recover@{app_id}", daemon=True).start()

    def get_available_hosts(self) -> list[HostState]:
        self.expire_hosts()
        with self._lock:
            return [HostState(ip=h.state.ip, slots=h.state.slots,
                              used_slots=h.state.used_slots,
                              n_devices=h.state.n_devices)
                    for h in self._hosts.values()]

    def set_next_evicted_host_ips(self, ips: list[str]) -> None:
        with self._lock:
            self._next_evicted_ips = set(ips)

    # ------------------------------------------------------------------
    # Multi-process device plane (parallel/distributed.py)
    # ------------------------------------------------------------------
    def join_device_plane(self, host: str,
                          n_processes: int) -> Optional[dict]:
        """Add ``host`` to the device-plane roster; once the roster is
        full, return this host's spec (callers poll until then). Process
        ids are assigned in join order and stay stable across polls; the
        first joiner's host runs the jax.distributed coordination
        service on a port claimed from its MPI pool. Reference analog:
        the cross-host plane MpiWorld builds per world
        (src/mpi/MpiWorld.cpp:1789-1934) — but formed ONCE per worker
        lifetime, like claiming a TPU pod slice."""
        with self._lock:
            dp = self._device_plane
            if dp["size"] == 0:
                dp["size"] = n_processes
            elif dp["size"] != n_processes:
                raise ValueError(
                    f"device plane already sized {dp['size']}, host "
                    f"{host} asked for {n_processes}")
            if host not in dp["roster"]:
                if len(dp["roster"]) >= dp["size"]:
                    raise ValueError(
                        f"device plane full ({dp['size']}); {host} "
                        "cannot join")
                dp["roster"].append(host)
            if len(dp["roster"]) < dp["size"]:
                return None
            if not dp["port"]:
                coord = dp["roster"][0]
                h = self._hosts.get(coord)
                # Fall back to the pool's last port if the coordinator
                # never registered (tests driving the planner directly)
                dp["port"] = (h.claim_mpi_port() if h is not None
                              else MPI_BASE_PORT + MPI_PORTS_PER_HOST - 1)
            return {"coordinator_host": dp["roster"][0],
                    "coordinator_port": dp["port"],
                    "num_processes": dp["size"],
                    "process_id": dp["roster"].index(host)}

    def clear_device_plane(self) -> None:
        with self._lock:
            dp = self._device_plane
            if dp["port"]:
                h = self._hosts.get(dp["roster"][0]) if dp["roster"] else None
                if h is not None:
                    h.release_mpi_port(dp["port"])
            self._device_plane = {"roster": [], "size": 0, "port": 0}

    # ------------------------------------------------------------------
    # The scheduling brain (reference Planner::callBatch)
    # ------------------------------------------------------------------
    def call_batch(self, req: BatchExecuteRequest) -> SchedulingDecision:
        """Schedule a batch. Accounting happens under the planner lock;
        network dispatch happens after it is released, so one unreachable
        worker cannot stall keep-alives and other apps' scheduling."""
        t0 = time.monotonic()
        with span("planner", "call_batch", app_id=req.app_id,
                  n_messages=req.n_messages()):
            try:
                return self._call_batch_inner(req)
            finally:
                _SCHEDULE_SECONDS.observe(time.monotonic() - t0)

    def _call_batch_inner(self, req: BatchExecuteRequest
                          ) -> SchedulingDecision:
        from faabric_tpu.proto import update_batch_exec_app_id

        # Messages must agree with their batch's app id — chained/scale
        # requests built from factories otherwise report results into the
        # wrong app bucket (reference updateBatchExecAppId)
        update_batch_exec_app_id(req, req.app_id)

        # Ledger t0 fallback for direct call_batch callers (the ingress
        # already stamped admit for everything that came through it)
        for m in req.messages:
            _LC.stamp_first(m, PHASE_ADMIT)

        with self._lock:
            scheduler = get_batch_scheduler()
            decision_type = scheduler.get_decision_type(self._in_flight, req)

            # A MIGRATION request that no longer classifies as DIST_CHANGE
            # raced completing results (check_migration snapshots outside
            # this lock): treat it as no-opportunity rather than letting it
            # masquerade as a scale-change or a fresh app
            if (req.type == int(BatchExecuteType.MIGRATION)
                    and decision_type != DecisionType.DIST_CHANGE):
                from faabric_tpu.batch_scheduler.decision import (
                    do_not_migrate_decision,
                )

                logger.debug("Migration request for app %d raced results; "
                             "ignoring", req.app_id)
                return do_not_migrate_decision()

            # Thaw: a NEW request for a frozen app resumes it
            thawing = False
            if decision_type == DecisionType.NEW and req.app_id in self._evicted:
                req = self._evicted.pop(req.app_id)
                decision_type = DecisionType.NEW
                thawing = True

            # Elastic scale-up: an OpenMP-style fork with the hint grows to
            # every free slot on its main host (reference Planner.cpp:833-893)
            if (decision_type == DecisionType.SCALE_CHANGE
                    and req.elastic_scale_hint and req.messages):
                self._apply_elastic_scale_locked(req)

            host_map = self._policy_host_map_locked()

            decision = None
            preloaded = self._preloaded.get(req.app_id)
            if preloaded is not None and decision_type in (
                    DecisionType.NEW, DecisionType.SCALE_CHANGE):
                decision = self._slice_preloaded_locked(preloaded, req)

            # Repeat fork-join shapes reuse their placement (reference
            # DecisionCache). NEW decisions only: scale-changes extend an
            # existing app's placement and must not consume or poison
            # entries keyed merely by (user, function, count).
            is_cacheable = (req.type == int(BatchExecuteType.THREADS)
                            and decision_type == DecisionType.NEW)
            from_cache = False
            if decision is None and is_cacheable:
                decision = self._decision_from_cache_locked(req, host_map)
                from_cache = decision is not None

            if decision is None:
                decision = scheduler.make_scheduling_decision(
                    host_map, self._in_flight, req)

            if (is_cacheable and not from_cache
                    and not is_sentinel_decision(decision)):
                from faabric_tpu.batch_scheduler import get_decision_cache

                get_decision_cache().add_cached_decision(
                    req, list(decision.hosts), 0)

            if decision.app_id == NOT_ENOUGH_SLOTS:
                if thawing:
                    # A failed thaw must NOT lose the parked app — re-park
                    # it so a later attempt (when capacity frees) succeeds
                    self._evicted[req.app_id] = req
                logger.warning("Not enough slots for app %d (%d msgs)",
                               req.app_id, req.n_messages())
                return decision

            if decision.app_id == MUST_FREEZE:
                self._freeze_app_locked(req)
                return decision

            if is_sentinel_decision(decision):  # DO_NOT_MIGRATE
                return decision

            if decision_type == DecisionType.NEW:
                decision, mappings, dispatches = self._handle_new_locked(req, decision)
            elif decision_type == DecisionType.SCALE_CHANGE:
                decision, mappings, dispatches = self._handle_scale_change_locked(
                    req, decision)
            else:
                decision, mappings, dispatches = self._handle_dist_change_locked(
                    req, decision)
            _LC.stamp_many(req.messages, PHASE_SCHED)

            if thawing:
                # A thawed app may land anywhere — typically NOT where it
                # froze (that host was being evicted). single_host=True
                # would make _do_dispatch skip the THREADS snapshot push
                # and the executor skip restore(), resuming the app on a
                # blank memory image. Force the multi-host path so the
                # planner-parked snapshot travels to the thaw host(s).
                for _, sub in dispatches:
                    sub.single_host = False

        # Network I/O strictly outside the lock: mappings first (guest code
        # blocks on wait_for_mappings before messaging), then dispatch.
        with self._lock:
            # Snapshot the decision (and the mappings, which for scale/
            # dist changes IS the live in-flight decision) under the
            # lock: results landing on other threads remove_message rows
            # concurrently — fast tasks can complete before the RPC
            # layer even serializes the response, and a clone taken
            # outside the lock could tear mid-copy
            result = decision.clone()
            mappings = mappings.clone()
            gids, hosts = self._group_hosts.get(req.app_id, (set(), set()))
            self._group_hosts[req.app_id] = (
                gids | {mappings.group_id}, hosts | set(mappings.hosts))
            _IN_FLIGHT_APPS.set(len(self._in_flight))
            if self._journal.enabled:
                self._journal_app_update_locked(req.app_id)
                _LC.stamp_many(req.messages, PHASE_JOURNAL)
        self._send_mappings(mappings)
        self._do_dispatch(dispatches)
        return result

    # ------------------------------------------------------------------
    # Batched scheduling ticks (ISSUE 8): the ingress coordinator hands
    # a whole tick's worth of NEW invocations to call_batch_group — one
    # lock pass, one host-map build + expiry sweep, the decision cache
    # as an admission fast path, one group-commit journal record, and
    # pipelined (per-host) mapping + dispatch RPCs.
    # ------------------------------------------------------------------
    @staticmethod
    def is_batchable_shape(req: BatchExecuteRequest) -> bool:
        """Lock-free half of the tick-eligibility check: a plain
        FUNCTIONS/PROCESSES batch with no MPI messages. The admission
        hot path uses ONLY this — probing planner state there would
        serialize every submission behind in-progress scheduling ticks,
        and the tick pass re-checks statefully under the lock anyway
        (requests that turn out to be scale-changes etc. are deferred
        to the classic path)."""
        if req.type not in (int(BatchExecuteType.FUNCTIONS),
                            int(BatchExecuteType.PROCESSES)):
            return False
        return bool(req.messages) and not any(m.is_mpi
                                              for m in req.messages)

    def call_batch_group(self, reqs: list[BatchExecuteRequest]
                         ) -> tuple[list[Optional[SchedulingDecision]],
                                    set[int]]:
        """Schedule one tick's batch of NEW invocations.

        Returns ``(results, deferred)``: ``results[i]`` is the detached
        decision clone, or ``None`` when the cluster had no capacity
        this tick (the caller requeues — slots free as results land);
        indices in ``deferred`` raced out of batch eligibility and must
        go through the classic ``call_batch``.

        Against the per-request path this amortises: ONE planner-lock
        acquisition and host-map/expiry pass for the whole batch, the
        decision cache as an admission fast path (a repeat signature
        skips the policy run), ONE group-commit journal record, and
        dispatch/mapping RPCs coalesced per host by the caller-facing
        tail of this method."""
        from faabric_tpu.batch_scheduler import get_decision_cache
        from faabric_tpu.proto import update_batch_exec_app_id

        results: list[Optional[SchedulingDecision]] = [None] * len(reqs)
        deferred: set[int] = set()
        mapping_clones: list[SchedulingDecision] = []
        dispatch_groups: dict[str, list[BatchExecuteRequest]] = {}
        journal_apps: list[int] = []
        cache = get_decision_cache()
        t0 = time.monotonic()
        with span("planner", "call_batch_group", n_requests=len(reqs)):
            with self._lock:
                scheduler = get_batch_scheduler()
                # ONE shared host-map view for the whole tick (includes
                # the expiry sweep), updated in place as claims land —
                # vs one build per request on the classic path
                view = self._policy_host_map_locked()
                # Free-slot watermark: when the cluster cannot fit a
                # request, it goes straight to the backlog WITHOUT a
                # policy run (or a cache lookup) — a full cluster must
                # make a tick cost one int compare per queued entry,
                # not one policy pass each (slots free as results land;
                # the next tick retries)
                free = sum(max(0, h.slots - h.used_slots)
                           for h in view.values())
                for i, req in enumerate(reqs):
                    update_batch_exec_app_id(req, req.app_id)
                    decision_type = scheduler.get_decision_type(
                        self._in_flight, req)
                    if (decision_type != DecisionType.NEW
                            or req.app_id in self._evicted
                            or req.app_id in self._preloaded):
                        deferred.add(i)
                        continue
                    if req.n_messages() > free:
                        continue  # results[i] stays None: backlog
                    decision = self._decision_from_cache_locked(req, view)
                    from_cache = decision is not None
                    cache.record_outcome(from_cache)
                    if decision is None:
                        decision = scheduler.make_scheduling_decision(
                            view, self._in_flight, req)
                    if decision.app_id == NOT_ENOUGH_SLOTS:
                        continue  # results[i] stays None: backlog
                    if is_sentinel_decision(decision):
                        deferred.add(i)
                        continue
                    if not from_cache:
                        cache.add_cached_decision(
                            req, list(decision.hosts), 0)
                    decision, mappings, dispatches = \
                        self._handle_new_locked(req, decision)
                    _LC.stamp_many(req.messages, PHASE_SCHED)
                    free -= decision.n_messages
                    for ip in decision.hosts:
                        h = view.get(ip)
                        if h is not None:
                            h.used_slots += 1
                    results[i] = decision.clone()
                    mapping_clones.append(mappings.clone())
                    gids, hosts = self._group_hosts.get(
                        req.app_id, (set(), set()))
                    self._group_hosts[req.app_id] = (
                        gids | {mappings.group_id},
                        hosts | set(mappings.hosts))
                    journal_apps.append(req.app_id)
                    for ip, sub in dispatches:
                        dispatch_groups.setdefault(ip, []).append(sub)
                if journal_apps and self._journal.enabled:
                    self._journal_group_commit_locked(journal_apps)
                    for subs in dispatch_groups.values():
                        for sub in subs:
                            _LC.stamp_many(sub.messages, PHASE_JOURNAL)
                _IN_FLIGHT_APPS.set(len(self._in_flight))
            # Network strictly outside the lock, coalesced per host:
            # mappings first (guest code blocks on wait_for_mappings
            # before messaging), then ONE dispatch RPC per (host, tick)
            if mapping_clones:
                from faabric_tpu.transport.ptp_remote import (
                    send_mappings_for_decisions,
                )

                send_mappings_for_decisions(mapping_clones)
            self._do_dispatch_pipelined(dispatch_groups)
        if journal_apps:
            _SCHEDULE_SECONDS.observe(
                (time.monotonic() - t0) / len(journal_apps))
        return results, deferred

    def _journal_group_commit_locked(self, app_ids: list[int]) -> None:
        """ONE group-commit journal record for the tick's scheduling
        mutations (vs one write-through append per app): same
        durability class as ``append_durable`` — in the kernel before
        dispatch — inside a single fsync boundary."""
        j = self._journal
        j.append_group([("app_update", self._app_update_fields_locked(a))
                        for a in app_ids])
        if j.since_compact >= j.compact_records:
            with span("journal", "compact", records=j.since_compact):
                j.compact(self._journal_snapshot_locked())

    def _do_dispatch_pipelined(
            self, groups: dict[str, list[BatchExecuteRequest]]) -> None:
        """One EXECUTE_BATCHES RPC per (host, tick) carrying every
        sub-batch bound for that host, instead of one RPC per app. A
        failed host fans its sub-batches into the normal dispatch
        recovery (requeue onto survivors)."""
        if not groups:
            return
        t0 = time.monotonic()

        def dispatch_one(ip: str, subs: list[BatchExecuteRequest]) -> None:
            try:
                if _FAULTS:
                    verdict = _FP_DISPATCH.fire(
                        host=ip, app_id=subs[0].app_id)
                    if verdict is DROP:
                        return
                for sub in subs:
                    _LC.stamp_many(sub.messages, PHASE_DISPATCH)
                self._get_client(ip).execute_functions_many(subs)
            except Exception:  # noqa: BLE001 — a dead host must not
                # stall the tick's other hosts
                logger.exception(
                    "Pipelined dispatch of %d app(s) to %s failed",
                    len(subs), ip)
                for sub in subs:
                    self._recover_dispatch(sub, ip, b"Dispatch failed")
                return
            logger.debug("Dispatched %d app(s) (%d msgs) to %s in "
                         "one RPC", len(subs),
                         sum(s.n_messages() for s in subs), ip)

        with span("planner", "dispatch_pipelined", n_hosts=len(groups)):
            if len(groups) == 1:
                ip, subs = next(iter(groups.items()))
                dispatch_one(ip, subs)
            else:
                # Hosts dispatch concurrently: the per-host RPCs run on
                # the shared tick thread, and serially one unreachable
                # host's connect/send timeout would head-of-line-block
                # every healthy host's frame AND all subsequent ticks.
                # Joined: a slow host costs one socket timeout, never an
                # unbounded dispatcher-thread pileup.
                workers = [threading.Thread(
                    target=dispatch_one, args=(ip, subs),
                    name=f"planner/dispatch@{ip}", daemon=True)
                    for ip, subs in groups.items()]
                for w in workers:
                    w.start()
                for w in workers:
                    w.join()
        _DISPATCH_SECONDS.observe(time.monotonic() - t0)

    def fail_unscheduled(self, req: BatchExecuteRequest,
                         reason: bytes) -> None:
        """Terminal path for a fire-and-forget submission shed before it
        was ever scheduled: record the expected count and FAILED results
        so batch-status pollers finish instead of hanging on an app the
        planner never placed."""
        with self._lock:
            if req.app_id in self._in_flight:
                return  # a schedule won the race; results arrive normally
            self._expected.setdefault(req.app_id, req.n_messages())
        for m in req.messages:
            m.return_value = int(ReturnValue.FAILED)
            m.output_data = reason
        try:
            self.set_message_results(req.messages)
        except Exception:  # noqa: BLE001
            logger.exception("Failing unscheduled app %d", req.app_id)
        with self._lock:
            if req.app_id not in self._completed_order:
                self._completed_order.append(req.app_id)
                self._evict_old_results_locked()

    # -- decision handling (all run under self._lock; they return the
    # mapping distribution + dispatches to perform after the lock drops) --
    def _handle_new_locked(self, req: BatchExecuteRequest,
                    decision: SchedulingDecision
                    ) -> tuple[SchedulingDecision, SchedulingDecision, list]:
        group_id = req.group_id or generate_gid()
        decision.group_id = group_id
        update_batch_exec_group_id(req, group_id)
        for i, msg in enumerate(req.messages):
            # Messages that didn't pick their own group idx (plain FUNCTIONS
            # batches) take their app idx, so every batch forms a usable
            # PTP group
            if decision.group_idxs[i] == 0 and decision.app_idxs[i] != 0:
                decision.group_idxs[i] = decision.app_idxs[i]
            msg.group_idx = decision.group_idxs[i]
        self._claim_for_decision_locked(decision, req)
        self._in_flight[req.app_id] = (req, decision)
        self._expected[req.app_id] = req.n_messages()
        self._next_idx[req.app_id] = 1 + max(
            (m.app_idx for m in req.messages), default=req.n_messages() - 1)
        self._results.setdefault(req.app_id, {})
        if req.messages and req.messages[0].is_mpi:
            # Placement-shape accounting for gang-scheduled worlds: the
            # hierarchical collectives' wire bytes scale with hosts and
            # ranks/host, so the shape IS the perf-relevant outcome
            topo = decision.topology()
            _metrics.counter(
                "faabric_planner_mpi_placements_total",
                "Scheduled MPI worlds by placement shape",
                hosts=str(topo.n_hosts),
                gang="1" if topo.hosts_contiguous() else "0").inc()
            logger.debug(
                "MPI world app=%d placed: %d rank(s) on %d host(s), "
                "max %d/host, contiguous=%s", req.app_id, topo.size,
                topo.n_hosts, topo.max_ranks_per_host,
                topo.hosts_contiguous())
        return decision, decision, self._build_dispatches(req, decision)

    def _handle_scale_change_locked(self, req: BatchExecuteRequest,
                             decision: SchedulingDecision
                             ) -> tuple[SchedulingDecision, SchedulingDecision, list]:
        old_req, old_decision = self._in_flight[req.app_id]
        update_batch_exec_group_id(req, old_decision.group_id)
        decision.group_id = old_decision.group_id

        # New messages continue the app's index space monotonically —
        # never derived from the remaining-message count, which shrinks as
        # results complete and would hand out duplicate group indices.
        for i, msg in enumerate(req.messages):
            if not msg.app_idx:
                msg.app_idx = self._next_idx[req.app_id]
                self._next_idx[req.app_id] += 1
            else:
                self._next_idx[req.app_id] = max(
                    self._next_idx[req.app_id], msg.app_idx + 1)
            msg.group_idx = msg.group_idx or msg.app_idx
            decision.app_idxs[i] = msg.app_idx
            decision.group_idxs[i] = msg.group_idx
            decision.message_ids[i] = msg.id

        self._claim_for_decision_locked(decision, req)

        # Merge into the in-flight record
        for i in range(decision.n_messages):
            old_decision.add_message(
                decision.hosts[i], decision.message_ids[i],
                decision.app_idxs[i], decision.group_idxs[i],
                decision.mpi_ports[i], decision.device_ids[i])
            old_req.messages.append(req.messages[i])
        self._expected[req.app_id] = (
            self._expected.get(req.app_id, 0) + req.n_messages())

        return decision, old_decision, self._build_dispatches(req, decision)

    def _handle_dist_change_locked(self, req: BatchExecuteRequest,
                            decision: SchedulingDecision
                            ) -> tuple[SchedulingDecision, SchedulingDecision, list]:
        old_req, old_decision = self._in_flight[req.app_id]

        # Transfer claims: release every old placement, then re-claim.
        # Unmoved messages keep their ports/devices (keep_from); moved ones
        # get fresh allocations.
        self._release_for_decision_locked(old_decision, old_req)
        self._claim_for_decision_locked(decision, old_req, keep_from=old_decision)

        new_group_id = generate_gid()
        decision.group_id = new_group_id
        self._num_migrations += 1

        update_batch_exec_group_id(old_req, new_group_id)
        self._in_flight[req.app_id] = (old_req, decision)
        # The migrating ranks re-dispatch themselves via the migration
        # exception + MIGRATION batch (reference §3.5); no dispatch here.
        return decision, decision, []

    def _apply_elastic_scale_locked(self, req: BatchExecuteRequest) -> None:
        """Grow the scale-change request so the app fills every free slot
        on its main host (called under the planner lock)."""
        import copy

        old_req, old_decision = self._in_flight[req.app_id]
        main_host = (old_req.messages[0].main_host
                     or old_decision.hosts[0]) if old_decision.hosts else ""
        host = self._hosts.get(main_host)
        if host is None:
            return
        extra = host.state.available - req.n_messages()
        template = req.messages[0]
        for _ in range(max(0, extra)):
            clone = copy.deepcopy(template)
            clone.id = generate_gid()
            clone.app_idx = 0  # assigned monotonically by scale handling
            clone.group_idx = 0
            req.messages.append(clone)
        if extra > 0:
            logger.debug("Elastic scale: app %d grows by %d to fill %s",
                         req.app_id, extra, main_host)

    # -- migration (reference Scheduler::checkForMigrationOpportunities
    # via the planner's DIST_CHANGE path, §3.5) --------------------------
    def check_migration(self, app_id: int) -> Optional[SchedulingDecision]:
        """Ask the policy whether the running app should move. Returns the
        new decision (fresh group id, mappings already distributed) or
        None when there is no improvement."""
        from faabric_tpu.batch_scheduler.decision import is_sentinel_decision

        with self._lock:
            in_flight = self._in_flight.get(app_id)
            if in_flight is None:
                return None
            cur_req, _ = in_flight
            mig_req = BatchExecuteRequest(
                app_id=app_id, user=cur_req.user, function=cur_req.function,
                type=int(BatchExecuteType.MIGRATION), subtype=cur_req.subtype)
            mig_req.messages = list(cur_req.messages)
        decision = self.call_batch(mig_req)
        if decision.app_id == MUST_FREEZE:
            return decision  # callers freeze their app (spot eviction)
        if is_sentinel_decision(decision):
            return None
        # call_batch already returns a detached clone — safe to hand out
        return decision

    def _freeze_app_locked(self, req: BatchExecuteRequest) -> None:
        """Park a running app: release its resources and remember the
        request for a later thaw (reference Planner.cpp:1005-1019)."""
        in_flight = self._in_flight.pop(req.app_id, None)
        if in_flight is not None:
            old_req, old_decision = in_flight
            self._release_for_decision_locked(old_decision, old_req)
            self._evicted[req.app_id] = old_req
        else:
            self._evicted[req.app_id] = req
        _IN_FLIGHT_APPS.set(len(self._in_flight))
        if self._journal.enabled:
            self._journal_append(
                "app_freeze", app_id=req.app_id,
                req=self._evicted[req.app_id].to_dict())

    def get_cluster_topology(self) -> dict:
        """Scheduler-readable cluster topology snapshot: per-host
        capacity plus the rank→host Topology of every in-flight
        gang-scheduled (MPI) world — the cluster-level counterpart of
        ``MpiWorld.topology()`` (one ``Topology`` per world, JSON-safe),
        for dashboards, tests and placement debugging."""
        with self._lock:
            hosts = {ip: {"slots": h.state.slots,
                          "used_slots": h.state.used_slots,
                          "n_devices": h.state.n_devices}
                     for ip, h in self._hosts.items()}
            worlds = {}
            for app_id, (req, dec) in self._in_flight.items():
                if req.n_messages() and req.messages[0].is_mpi:
                    worlds[app_id] = dec.topology().to_dict()
        return {"hosts": hosts, "worlds": worlds}

    # -- resource accounting ---------------------------------------------
    def _policy_host_map_locked(self) -> dict[str, HostState]:
        self.expire_hosts()
        out: dict[str, HostState] = {}
        for ip, h in self._hosts.items():
            out[ip] = HostState(
                ip=ip, slots=h.state.slots, used_slots=h.state.used_slots,
                n_devices=h.state.n_devices,
                for_eviction=ip in self._next_evicted_ips)
        return out

    def _claim_for_decision_locked(self, decision: SchedulingDecision,
                            req: BatchExecuteRequest,
                            keep_from: SchedulingDecision | None = None) -> None:
        is_mpi = req.n_messages() > 0 and req.messages[0].is_mpi
        for i, ip in enumerate(decision.hosts):
            host = self._hosts.get(ip)
            if host is None:
                continue
            host.state.claim(1)
            if keep_from is not None and keep_from.hosts[i] == ip:
                # Unmoved rank: re-claim its previous port/device
                port = keep_from.mpi_ports[i]
                dev = keep_from.device_ids[i]
                if port:
                    host.used_mpi_ports.add(port)
                if 0 <= dev < len(host.device_load):
                    host.device_load[dev] += 1
                decision.mpi_ports[i] = port
                decision.device_ids[i] = dev
            else:
                decision.mpi_ports[i] = host.claim_mpi_port() if is_mpi else 0
                decision.device_ids[i] = host.claim_device()

    def _release_for_decision_locked(self, decision: SchedulingDecision,
                              req: BatchExecuteRequest) -> None:
        for i, ip in enumerate(decision.hosts):
            host = self._hosts.get(ip)
            if host is None:
                continue
            host.state.free(1)
            if decision.mpi_ports[i]:
                host.release_mpi_port(decision.mpi_ports[i])
            host.release_device(decision.device_ids[i])

    def _release_message_locked(self, app_id: int, msg_id: int) -> None:
        in_flight = self._in_flight.get(app_id)
        if in_flight is None:
            return
        _, decision = in_flight
        try:
            i = decision.message_ids.index(msg_id)
        except ValueError:
            return
        host = self._hosts.get(decision.hosts[i])
        if host is not None:
            host.state.free(1)
            if decision.mpi_ports[i]:
                host.release_mpi_port(decision.mpi_ports[i])
            host.release_device(decision.device_ids[i])

    # ------------------------------------------------------------------
    # Automatic recovery: requeue-with-backoff (the planner is the
    # cluster's single recovery authority — worker loss mid-batch moves
    # the affected messages to survivors under a per-app retry budget
    # instead of terminally failing them)
    # ------------------------------------------------------------------
    def _fail_messages(self, msgs: list[Message], reason: bytes) -> None:
        """Terminal path: record FAILED results so batch waiters
        unblock. First-write-wins in set_message_result still protects a
        genuine late result racing this."""
        for m in msgs:
            with self._lock:
                if m.id in self._results.get(m.app_id, {}):
                    continue
            m.return_value = int(ReturnValue.FAILED)
            m.output_data = reason
            try:
                self.set_message_result(m)
            except Exception:  # noqa: BLE001
                logger.exception("Failing msg %d", m.id)

    def _recover_messages(self, app_id: int, msgs: list[Message],
                          reason: bytes) -> None:
        """Recovery state machine entry (runs on its own thread, never
        under the planner lock's callers):

        ``failed`` → (budget left, app retryable) → backoff → ``requeue``
        onto surviving hosts → re-dispatch; otherwise → terminal FAILED.

        MPI batches are not requeued: a world's collective state dies
        with its ranks — surviving ranks get a bounded MpiWorldAborted
        from the transport layer instead, and the guest (or its
        checkpoint/restore loop) owns the restart. THREADS batches
        requeue naturally: dispatch re-pushes the app's registered
        snapshot to the new host before the tasks restore."""
        t_detect = time.monotonic()
        conf = get_system_config()
        with self._lock:
            msgs = [m for m in msgs
                    if m.id not in self._results.get(app_id, {})]
            if not msgs:
                return
            record = self._in_flight.get(app_id)
            in_flight = record is not None
            used = self._requeue_attempts.get(app_id, 0)
            # MPI detection must scan the WHOLE app, not just the doomed
            # subset: the root message of an MPI batch often has
            # is_mpi=False on the planner's copy (it is set worker-side
            # during create_world) — but the scale-up rank messages the
            # world chained through us carry it, so once a world exists
            # anywhere, the app reads as MPI here. A root that died
            # BEFORE chaining its ranks has no world to corrupt and may
            # requeue like any plain function.
            app_is_mpi = in_flight and any(m.is_mpi
                                           for m in record[0].messages)
            retryable = (in_flight and not app_is_mpi
                         and not any(m.is_mpi for m in msgs)
                         and used < conf.planner_max_requeues)
            if retryable:
                self._requeue_attempts[app_id] = used + 1
        # Black-box entry: a recovery pass is exactly the moment a
        # post-mortem wants the planner's recent history on disk.
        # Recorded AFTER the already-completed filter and with the
        # actual decision, so the dump never claims a requeue of
        # messages that were in fact failed (or already done).
        flight_record("planner_recovery", app=app_id,
                      n_messages=len(msgs), retryable=retryable,
                      reason=reason.decode("utf-8", "replace"))
        flight_dump("planner_recovery")
        if not retryable:
            if in_flight and used >= conf.planner_max_requeues:
                _RETRY_EXHAUSTED.inc(len(msgs))
                logger.warning(
                    "Requeue budget (%d) exhausted for app %d; failing "
                    "%d msgs", conf.planner_max_requeues, app_id, len(msgs))
            self._fail_messages(msgs, reason)
            return
        # Exponential backoff + jitter before re-placing: an immediate
        # requeue would race the failure that displaced us (a flapping
        # host re-registering, a planner-side connection reset) and
        # synchronized retries from many apps would stampede survivors
        time.sleep(self._requeue_delay(used))
        self._requeue(app_id, msgs, t_detect, reason)

    @staticmethod
    def _requeue_delay(used: int) -> float:
        """One schedule implementation for all recovery backoff: the
        transport clients' RetryPolicy with the planner's base knob."""
        from faabric_tpu.util.retry import RetryPolicy

        conf = get_system_config()
        return RetryPolicy(
            max_attempts=conf.planner_max_requeues + 1,
            backoff=conf.planner_requeue_backoff,
            max_backoff=30.0).delay(used)

    def _requeue(self, app_id: int, msgs: list[Message], t_detect: float,
                 reason: bytes) -> None:
        """Move the affected messages onto surviving hosts: release the
        dead placements, re-place through the scheduling policy, merge
        the new rows into the live decision, then re-send mappings and
        re-dispatch (network strictly outside the lock)."""
        from faabric_tpu.batch_scheduler.decision import is_sentinel_decision

        fail: Optional[list[Message]] = None
        fail_reason = reason
        retry_later = False
        conf = get_system_config()
        with self._lock:
            pending = [m for m in msgs
                       if m.id not in self._results.get(app_id, {})]
            if not pending:
                return  # genuine late results won every race
            todo = [m.id for m in pending]
            todo_set = set(todo)
            in_flight = self._in_flight.get(app_id)
            if in_flight is None:
                # The app left _in_flight during the backoff: our rows
                # were already extracted, so the other messages' results
                # drove n_messages to 0 and "completed" the app. These
                # messages have no placement and no results — they MUST
                # fail now or the batch stays unfinishable forever
                # (finished requires len(results) >= expected).
                fail = pending
                fail_reason = reason + b" (app completed around requeue)"
            else:
                req, decision = in_flight
                for mid in todo:
                    # Rows may already be extracted by an earlier
                    # no-capacity round of this same recovery; only live
                    # rows release
                    if mid in decision.message_ids:
                        self._release_message_locked(app_id, mid)  # dead: no-op
                        decision.remove_message(mid)
                retry_msgs = [m for m in req.messages if m.id in todo_set]
                sub = BatchExecuteRequest(
                    app_id=req.app_id, group_id=req.group_id, user=req.user,
                    function=req.function, type=req.type,
                    subtype=req.subtype, snapshot_key=req.snapshot_key)
                sub.messages = retry_msgs
                host_map = self._policy_host_map_locked()
                scheduler = get_batch_scheduler()
                # Empty in-flight view: the retry slice places like a NEW
                # batch of just these messages (their app/group idxs ride
                # along on the messages themselves)
                new_decision = scheduler.make_scheduling_decision(
                    host_map, {}, sub)
                if is_sentinel_decision(new_decision):
                    # No capacity right now. Capacity frees as running
                    # messages complete, so spend another budget unit on
                    # a longer-backoff round rather than failing outright.
                    used = self._requeue_attempts.get(app_id, 0)
                    if used < conf.planner_max_requeues:
                        used += 1
                        self._requeue_attempts[app_id] = used
                        retry_later = True
                    else:
                        fail = retry_msgs
                        fail_reason = reason + b" (no requeue capacity)"
                else:
                    new_decision.group_id = decision.group_id
                    self._claim_for_decision_locked(new_decision, sub)
                    for i in range(new_decision.n_messages):
                        decision.add_message(
                            new_decision.hosts[i],
                            new_decision.message_ids[i],
                            new_decision.app_idxs[i],
                            new_decision.group_idxs[i],
                            new_decision.mpi_ports[i],
                            new_decision.device_ids[i])
                    dispatches = self._build_dispatches(sub, new_decision)
                    # A requeued slice of a multi-host app must not claim
                    # single-host: the flag gates THREADS snapshot
                    # pushes, and the new host needs the snapshot
                    single = len(decision.unique_hosts()) == 1
                    for _, s in dispatches:
                        s.single_host = single
                    mappings = decision.clone()
                    gids, hosts = self._group_hosts.get(app_id,
                                                        (set(), set()))
                    self._group_hosts[app_id] = (
                        gids | {mappings.group_id},
                        hosts | set(mappings.hosts))
                    _REQUEUES_TOTAL.inc()
                    _REQUEUED_MESSAGES.inc(len(todo))
                    if self._journal.enabled:
                        # Requeue outcome is durable: the moved rows are
                        # in the live decision now — journal the merged
                        # record (plus a forensic marker journaldump
                        # renders on its own line)
                        self._journal_append(
                            "requeued", app_id=app_id,
                            n_messages=len(todo),
                            hosts=sorted(set(new_decision.hosts)))
                        self._journal_app_update_locked(app_id)
        if retry_later:
            # ``used`` was captured under the lock when the budget unit
            # was spent — re-reading _requeue_attempts here would race a
            # concurrent recovery round's increment (concheck:
            # guard-unlocked on the old read)
            delay = self._requeue_delay(used)
            logger.warning(
                "No capacity to requeue %d msgs of app %d yet; retrying "
                "in %.2fs (attempt %d/%d)", len(todo), app_id, delay,
                used, conf.planner_max_requeues)
            time.sleep(delay)
            self._requeue(app_id, pending, t_detect, reason)
            return
        if fail is not None:
            logger.warning("Failing %d unrecoverable msgs of app %d: %s",
                           len(fail), app_id, fail_reason.decode())
            _RETRY_EXHAUSTED.inc(len(fail))
            self._fail_messages(fail, fail_reason)
            return
        logger.warning("Requeued %d msgs of app %d onto %s after: %s",
                       len(todo), app_id,
                       sorted(set(new_decision.hosts)), reason.decode())
        # Ledger boundary (ISSUE 14): the requeue stamp splits the dead
        # first attempt from the re-dispatch — a recovered invocation's
        # result carries a ledger spanning BOTH attempts
        _LC.stamp_many(retry_msgs, PHASE_REQUEUE)
        flight_record("planner_requeued", app=app_id, n_messages=len(todo),
                      hosts=sorted(set(new_decision.hosts)))
        self._send_mappings(mappings)
        self._do_dispatch(dispatches)
        _RECOVERY_SECONDS.observe(time.monotonic() - t_detect)

    def _recover_dispatch(self, sub: BatchExecuteRequest, ip: str,
                          reason: bytes) -> None:
        """A failed dispatch re-enters the recovery machine on its own
        thread (the caller may hold no lock but sits on the dispatch
        path — the backoff sleep must not stall sibling dispatches)."""
        threading.Thread(
            target=self._recover_messages,
            args=(sub.app_id, list(sub.messages), reason),
            name=f"planner/recover@{sub.app_id}", daemon=True).start()

    def _decision_from_cache_locked(self, req: BatchExecuteRequest,
                             host_map) -> Optional[SchedulingDecision]:
        """Rebuild a decision from the cached placement of an identical
        fork shape, if the cached hosts still have capacity AND still
        pass the active policy's host filter."""
        from faabric_tpu.batch_scheduler import (
            get_batch_scheduler,
            get_decision_cache,
        )

        cached = get_decision_cache().get_cached_decision(req)
        if cached is None:
            return None
        hosts = cached.hosts
        need: dict[str, int] = {}
        for ip in hosts:
            need[ip] = need.get(ip, 0) + 1
        for ip, n in need.items():
            h = host_map.get(ip)
            if h is None or h.available < n or h.for_eviction:
                # Topology changed / host leaving: fall back to the policy
                return None
        # The policy's filter is part of placement correctness, not just
        # preference — compact uses it for tenant isolation (a cached
        # host may have acquired ANOTHER tenant's app since the entry
        # was written), spot for eviction. Probe it with just the needed
        # hosts: any removal invalidates the cached placement. The
        # default (bin-pack) filter is a no-op, so the steady-state fast
        # path pays one tiny dict build.
        probe = {ip: host_map[ip] for ip in need}
        if get_batch_scheduler().filter_hosts(probe, self._in_flight, req):
            return None
        decision = SchedulingDecision(req.app_id, 0)
        for i, msg in enumerate(req.messages):
            decision.add_message(hosts[i], msg.id, msg.app_idx,
                                 msg.group_idx)
        logger.debug("Reused cached placement for %s/%s×%d", req.user,
                     req.function, req.n_messages())
        return decision

    # -- preload ----------------------------------------------------------
    def preload_scheduling_decision(self, decision: SchedulingDecision) -> None:
        with self._lock:
            self._preloaded[decision.app_id] = decision
            logger.debug("Preloaded decision for app %d (%d msgs)",
                         decision.app_id, decision.n_messages)

    def _slice_preloaded_locked(self, preloaded: SchedulingDecision,
                         req: BatchExecuteRequest
                         ) -> Optional[SchedulingDecision]:
        """Take the preloaded rows matching this request's app idxs
        (reference Planner.cpp:1121-1136). Returns None — falling back to
        the policy — when the preload doesn't cover the request, names an
        unknown host, or would oversubscribe one (a preload is an operator
        hint recorded ahead of time; by use time other apps may have taken
        the slots, and honoring it blindly would corrupt accounting)."""
        out = SchedulingDecision(req.app_id, preloaded.group_id)
        by_idx = {preloaded.app_idxs[i]: i for i in range(preloaded.n_messages)}
        need: dict[str, int] = {}
        for msg in req.messages:
            i = by_idx.get(msg.app_idx)
            if i is None:
                logger.warning(
                    "Preloaded decision for app %d lacks app_idx %d; "
                    "falling back to the policy", req.app_id, msg.app_idx)
                return None
            out.add_message(preloaded.hosts[i], msg.id, msg.app_idx,
                            preloaded.group_idxs[i])
            need[preloaded.hosts[i]] = need.get(preloaded.hosts[i], 0) + 1
        for ip, n in need.items():
            h = self._hosts.get(ip)
            if h is None or h.state.slots - h.state.used_slots < n:
                logger.warning(
                    "Preloaded decision for app %d needs %d slots on %s "
                    "(unavailable); falling back to the policy",
                    req.app_id, n, ip)
                return None
        return out

    # ------------------------------------------------------------------
    # Dispatch (reference Planner::dispatchSchedulingDecision)
    # ------------------------------------------------------------------
    def _build_dispatches(self, req: BatchExecuteRequest,
                          decision: SchedulingDecision
                          ) -> list[tuple[str, BatchExecuteRequest]]:
        """Build the per-host sub-batches under the lock; the network sends
        happen afterwards in _do_dispatch."""
        per_host: dict[str, list[int]] = {}
        for i, ip in enumerate(decision.hosts):
            per_host.setdefault(ip, []).append(i)

        single_host = len(per_host) == 1
        out: list[tuple[str, BatchExecuteRequest]] = []
        for ip, idxs in per_host.items():
            sub = BatchExecuteRequest(
                app_id=req.app_id, group_id=req.group_id, user=req.user,
                function=req.function, type=req.type, subtype=req.subtype,
                single_host=single_host, snapshot_key=req.snapshot_key,
            )
            sub.messages = [req.messages[i] for i in idxs]
            out.append((ip, sub))
        return out

    def _do_dispatch(self, dispatches: list[tuple[str, BatchExecuteRequest]]) -> None:
        t0 = time.monotonic()
        with span("planner", "dispatch", n_hosts=len(dispatches)):
            self._do_dispatch_inner(dispatches)
        if dispatches:
            _DISPATCH_SECONDS.observe(time.monotonic() - t0)

    def _do_dispatch_inner(self,
                           dispatches: list[tuple[str, BatchExecuteRequest]]
                           ) -> None:
        for ip, sub in dispatches:
            is_threads = sub.type == int(BatchExecuteType.THREADS)
            if is_threads and not sub.single_host:
                if not self._push_snapshot_for_threads(sub, ip):
                    # Dispatching without the snapshot would hang the
                    # batch in restore(); recover the messages onto a
                    # host that can be given it
                    self._recover_dispatch(sub, ip, b"Snapshot push failed")
                    continue
            try:
                if _FAULTS:
                    verdict = _FP_DISPATCH.fire(host=ip, app_id=sub.app_id)
                    if verdict is DROP:
                        # Injected silent dispatch loss: the messages
                        # strand until the target's keep-alive expiry
                        # recovers them — the chaos scenario dispatch-
                        # time error handling cannot see
                        continue
                _LC.stamp_many(sub.messages, PHASE_DISPATCH)
                self._get_client(ip).execute_functions(sub)
            except Exception:  # noqa: BLE001 — a dead host must not stall others
                logger.exception("Dispatch of app %d to %s failed",
                                 sub.app_id, ip)
                self._recover_dispatch(sub, ip, b"Dispatch failed")
                continue
            logger.debug("Dispatched %d msgs of app %d to %s",
                         sub.n_messages(), sub.app_id, ip)

    def _push_snapshot_for_threads(self, req: BatchExecuteRequest,
                                   host: str) -> bool:
        """Push the main-thread snapshot ahead of remote THREADS dispatch
        (reference Planner.cpp:1334-1360). Returns False when the target
        host cannot be given the snapshot it needs to restore."""
        key = req.snapshot_key
        if not key:
            return True  # nothing to restore from
        main_host = req.messages[0].main_host if req.messages else ""
        if host == main_host:
            return True  # the main host already owns the snapshot
        snap = self.snapshot_registry.try_get_snapshot(key)
        if snap is None:
            logger.warning("No snapshot %s on planner for THREADS dispatch",
                           key)
            return False
        try:
            self._snapshot_clients.get(host).push_snapshot(key, snap)
            return True
        except Exception:  # noqa: BLE001
            logger.exception("Failed pushing snapshot %s to %s", key, host)
            return False

    def _send_mappings(self, decision: SchedulingDecision) -> None:
        """Distribute group mappings to every involved host's PTP server
        (reference PointToPointBroker::
        setAndSendMappingsFromSchedulingDecision)."""
        from faabric_tpu.transport.ptp_remote import send_mappings_from_decision

        send_mappings_from_decision(decision)

    def _get_client(self, ip: str):
        return self._clients.get(ip)

    # ------------------------------------------------------------------
    # Results (reference Planner::setMessageResult / getMessageResult)
    # ------------------------------------------------------------------
    def set_message_result(self, msg: Message) -> None:
        self.set_message_results([msg])

    def set_message_results(self, msgs: list[Message]) -> None:
        """Record one or many results. The batched form is the receive
        side of the coalesced result plane (ISSUE 8): one planner-lock
        pass over the whole frame, waiter pushes collected and sent
        after the lock, and group cleanups coalesced into ONE
        clear-groups RPC per host instead of one per completed app."""
        pushes: list[tuple] = []  # (client, msg)
        cleanups: dict[str, set[int]] = {}  # host → finished group ids
        redispatches: list[tuple] = []
        recorded: list[Message] = []  # lifecycle fold targets
        with self._lock:
            for msg in msgs:
                app_id, msg_id = msg.app_id, msg.id

                migrated = msg.return_value == int(ReturnValue.MIGRATED)
                frozen = msg.return_value == int(ReturnValue.FROZEN)
                if migrated:
                    # The rank vacated its old host; its new placement
                    # is already in the post-migration decision —
                    # re-dispatch it there as a MIGRATION batch
                    # (reference §3.5)
                    redispatch = self._build_migration_redispatch_locked(
                        app_id, msg_id)
                    if redispatch is not None:
                        redispatches.append(redispatch)
                if not migrated and not frozen:
                    if not self._record_result_locked(msg):
                        continue
                    _LC.stamp(msg, PHASE_RECORDED)
                    recorded.append(msg)
                    if self._journal.enabled:
                        # Lazy fields: the drain thread runs to_dict.
                        # Safe — a stored result is never mutated
                        # afterwards (the first-write-wins store is
                        # also the read source)
                        self._journal_append_fields(
                            "result", lambda m=msg: {"msg": m.to_dict()})

                waiters = self._waiters.pop((app_id, msg_id), set())
                for ip in waiters:
                    pushes.append((self._get_client(ip), msg))
                if app_id not in self._in_flight:
                    group_cleanup = self._group_hosts.pop(app_id, None)
                    if group_cleanup is not None:
                        gids, hosts = group_cleanup
                        for host in hosts:
                            cleanups.setdefault(host, set()).update(gids)

        # Fold the recorded ledgers into the per-phase digest + SLO
        # tracker OUTSIDE the lock (a fold is ~10 µs per message)
        if recorded and _LC.enabled:
            from faabric_tpu.telemetry import get_lifecycle_stats

            get_lifecycle_stats().fold(recorded)

        # Push results + group cleanup outside the lock (network)
        for client, msg in pushes:
            try:
                client.set_message_result(msg)
            except Exception:  # noqa: BLE001
                logger.exception("Failed pushing result %d to waiter",
                                 msg.id)
        if cleanups:
            from faabric_tpu.transport.ptp_remote import send_clear_groups

            for host, gids in cleanups.items():
                send_clear_groups(host, sorted(gids))

        for redispatch in redispatches:
            self._do_dispatch([redispatch])

    def _record_result_locked(self, msg: Message,
                              replay: bool = False) -> bool:
        """The pure state mutation of a (non-migration, non-freeze)
        result: first-write-wins store, slot release, in-flight row
        removal and completion bookkeeping. Shared verbatim by the live
        path and journal replay so a replayed planner lands in exactly
        the state the crashed one held. Returns False on a duplicate."""
        app_id, msg_id = msg.app_id, msg.id
        if msg_id in self._results.get(app_id, {}):
            # First write wins (ADVICE r5): a synthetic FAILED
            # result (host expiry) racing a genuine late result —
            # or a duplicate report — must never overwrite the
            # recorded result. The first write already released
            # the slot and notified waiters; late readers get
            # the stored result from get_message_result.
            logger.debug("Ignoring duplicate result for msg %d "
                         "(app %d)", msg_id, app_id)
            return False
        self._release_message_locked(app_id, msg_id)
        self._results.setdefault(app_id, {})[msg_id] = msg
        if not replay:
            self._results_count += 1
            if msg.return_value == int(ReturnValue.FAILED):
                self._results_failed += 1
            _RESULTS_TOTAL.inc()
            if msg.timestamp:
                _RESULT_ROUNDTRIP.observe(
                    max(0.0, time.time() - msg.timestamp))

        in_flight = self._in_flight.get(app_id)
        if in_flight is not None:
            req, decision = in_flight
            decision.remove_message(msg_id)
            for i, m in enumerate(req.messages):
                if m.id == msg_id:
                    del req.messages[i]
                    break
            if decision.n_messages == 0:
                del self._in_flight[app_id]
                self._next_idx.pop(app_id, None)
                self._preloaded.pop(app_id, None)
                self._requeue_attempts.pop(app_id, None)
                if app_id not in self._completed_order:
                    self._completed_order.append(app_id)
                self._evict_old_results_locked()
                logger.debug("App %d complete", app_id)
            _IN_FLIGHT_APPS.set(len(self._in_flight))
        if replay and app_id not in self._in_flight:
            # The live path pops this for the group-cleanup broadcast
            # (set_message_result); replay must land in the same state
            # without the network side effect
            self._group_hosts.pop(app_id, None)
        return True

    def _build_migration_redispatch_locked(self, app_id: int, msg_id: int
                                    ) -> Optional[tuple[str, BatchExecuteRequest]]:
        """Under the lock: build the MIGRATION sub-batch that moves one
        migrated rank to its post-migration host."""
        in_flight = self._in_flight.get(app_id)
        if in_flight is None:
            return None
        req, decision = in_flight
        try:
            i = decision.message_ids.index(msg_id)
        except ValueError:
            return None
        target = decision.hosts[i]
        for m in req.messages:
            if m.id == msg_id:
                m.return_value = 0
                m.output_data = b""
                sub = BatchExecuteRequest(
                    app_id=req.app_id, group_id=req.group_id, user=req.user,
                    function=req.function,
                    type=int(BatchExecuteType.MIGRATION),
                    subtype=req.subtype, snapshot_key=req.snapshot_key)
                sub.messages = [m]
                logger.debug("Re-dispatching migrated msg %d to %s",
                             msg_id, target)
                return (target, sub)
        return None

    # The planner is cluster-singleton and long-lived: completed apps'
    # results are retained for late readers but bounded, oldest-first.
    MAX_KEPT_APP_RESULTS = 1000

    def _evict_old_results_locked(self) -> None:
        while len(self._completed_order) > self.MAX_KEPT_APP_RESULTS:
            oldest = self._completed_order.pop(0)
            self._results.pop(oldest, None)
            self._expected.pop(oldest, None)

    def get_message_result(self, app_id: int, msg_id: int,
                           waiting_host: str = "") -> Optional[Message]:
        """Return the result if known; otherwise register the waiting host
        for a push when it lands (reference Planner.cpp:543-589)."""
        with self._lock:
            result = self._results.get(app_id, {}).get(msg_id)
            if result is not None:
                return result
            if waiting_host:
                self._waiters.setdefault((app_id, msg_id), set()).add(waiting_host)
            return None

    def get_batch_results(self, app_id: int) -> BatchExecuteRequestStatus:
        with self._lock:
            results = list(self._results.get(app_id, {}).values())
            expected = self._expected.get(app_id, 0)
            return BatchExecuteRequestStatus(
                app_id=app_id,
                finished=(app_id not in self._in_flight
                          and expected > 0 and len(results) >= expected),
                message_results=results,
                expected_num_messages=expected,
            )

    def get_scheduling_decision(self, app_id: int) -> Optional[SchedulingDecision]:
        with self._lock:
            in_flight = self._in_flight.get(app_id)
            # Snapshot: the live decision mutates as results land
            return in_flight[1].clone() if in_flight else None

    # ------------------------------------------------------------------
    # State master registry
    # ------------------------------------------------------------------
    def claim_state_master(self, user: str, key: str,
                           claiming_host: str) -> tuple[str, str, int]:
        """Return ``(master, backup, epoch)`` for a state key, claiming
        mastership for the caller if unowned (the Redis getMasterIP(claim)
        analog, grown a replica placement and a fencing epoch, ISSUE 19).

        Fresh claims elect the claimer as master (locality: first writer
        is usually the hottest), a consistent-hash backup among the other
        live hosts, and bump the epoch. A recorded master that fell out
        of the host registry fails over to its live backup (promotion —
        same transition the keep-alive reaper performs) or, with no live
        backup, re-elects the claimer. With ``FAABRIC_STATE_REPLICAS=0``
        backups stay empty and the epoch stays 0 — seed-era semantics.
        The registry-emptiness guard keeps planner-only unit setups (no
        registered hosts at all) on plain first-claimer semantics."""
        full = f"{user}/{key}"
        replicas = get_system_config().state_replicas
        promoted: list[tuple[str, str, str, int]] = []
        with self._lock:
            master = self._state_masters.get(full)
            stale = (master is not None and self._hosts
                     and master not in self._hosts)
            if master is None or stale:
                backup = self._state_backups.get(full, "")
                epoch = (self._state_epochs.get(full, 0) + 1
                         if replicas > 0 else self._state_epochs.get(full, 0))
                if stale and backup and backup in self._hosts:
                    # The dead master's replica holds every acked write:
                    # promote it rather than electing the claimer over
                    # an empty image
                    master = backup
                    new_backup = self._elect_backup_locked(full, {master})
                    logger.warning(
                        "State master for %s is not registered; promoting "
                        "backup %s (epoch %d)", full, master, epoch)
                    self._state_masters[full] = master
                    self._state_backups[full] = new_backup
                    self._state_epochs[full] = epoch
                    if self._journal.enabled:
                        self._journal_append("state_failover", key=full,
                                             host=master, backup=new_backup,
                                             epoch=epoch)
                    promoted.append((full, master, new_backup, epoch))
                else:
                    if stale:
                        logger.warning(
                            "State master %s for %s is not registered; "
                            "re-electing %s", master, full, claiming_host)
                    master = claiming_host
                    self._state_masters[full] = master
                    self._state_backups[full] = self._elect_backup_locked(
                        full, {master})
                    if replicas > 0:
                        self._state_epochs[full] = epoch
                    if self._journal.enabled:
                        self._journal_append(
                            "state_claim", key=full, host=master,
                            backup=self._state_backups[full], epoch=epoch)
            elif replicas > 0 and self._hosts:
                # Live master: lazily heal a dead/absent backup (no epoch
                # bump — ownership did not change)
                backup = self._state_backups.get(full, "")
                if not backup or backup not in self._hosts:
                    new_backup = self._elect_backup_locked(full, {master})
                    if new_backup != backup:
                        self._state_backups[full] = new_backup
                        if self._journal.enabled:
                            self._journal_append("state_backup", key=full,
                                                 backup=new_backup)
            placement = (master, self._state_backups.get(full, ""),
                         self._state_epochs.get(full, 0))
        if promoted:
            self._dispatch_state_promotions(promoted)
        return placement

    def drop_state_master(self, user: str, key: str) -> None:
        with self._lock:
            dropped = self._state_masters.pop(f"{user}/{key}", None)
            self._state_backups.pop(f"{user}/{key}", None)
            # The epoch survives the drop: the next claim must fence out
            # any process still holding the old mastership
            if dropped is not None and self._journal.enabled:
                self._journal_append("state_drop", key=f"{user}/{key}")

    def state_placement(self) -> dict[str, dict]:
        """Authoritative per-key placement for /statemap: full key →
        {master, backup, epoch}."""
        with self._lock:
            return {
                full: {"master": master,
                       "backup": self._state_backups.get(full, ""),
                       "epoch": self._state_epochs.get(full, 0)}
                for full, master in self._state_masters.items()}

    # ------------------------------------------------------------------
    # Crash safety: write-ahead journal + restart replay + reconcile
    # (planner/journal.py; ISSUE 4)
    # ------------------------------------------------------------------
    def _journal_append(self, kind: str, **fields) -> None:
        """Append one mutation record (call sites hold the planner
        lock, so journal order IS state order) and fold the log into a
        snapshot when it crosses the compaction threshold.

        ``result`` records ride the journal's write-behind buffer (the
        hot path; a crash-lost tail is re-delivered by the workers'
        recent-results flush); every scheduling-class record is written
        through before the planner acts on it."""
        self._journal_append_fields(kind, fields)

    def _journal_append_fields(self, kind: str, fields) -> None:
        j = self._journal
        if kind == "result":
            j.append(kind, fields)
        else:
            j.append_durable(kind, fields)
        if j.since_compact >= j.compact_records:
            with span("journal", "compact", records=j.since_compact):
                j.compact(self._journal_snapshot_locked())

    def _app_update_fields_locked(self, app_id: int) -> dict:
        """One app_update record's fields: the app's live in-flight
        record (request + decision + index bookkeeping) — the one
        record kind that captures scheduling mutations of every
        decision type, including requeue merges. If the app already
        completed (fast tasks can finish before call_batch re-takes the
        lock), only the expected count is durable — its results carry
        the rest."""
        fields: dict = {
            "app_id": app_id,
            "expected": self._expected.get(app_id, 0),
            "next_idx": self._next_idx.get(app_id, 0),
        }
        gids, ghosts = self._group_hosts.get(app_id, (set(), set()))
        fields["group"] = [sorted(gids), sorted(ghosts)]
        in_flight = self._in_flight.get(app_id)
        if in_flight is not None:
            req, decision = in_flight
            fields["req"] = req.to_dict()
            fields["decision"] = decision.to_dict()
        return fields

    def _journal_app_update_locked(self, app_id: int) -> None:
        self._journal_append("app_update",
                             **self._app_update_fields_locked(app_id))

    def _journal_snapshot_locked(self) -> dict:
        """The full durable state, as one JSON-serializable dict — the
        compaction target and the shape `_apply_journal_snapshot_locked`
        restores. Dict keys become strings in JSON; apply converts
        back."""
        return {
            "in_flight": {
                str(a): {"req": req.to_dict(), "decision": d.to_dict()}
                for a, (req, d) in self._in_flight.items()},
            "results": {
                str(a): {str(mid): m.to_dict() for mid, m in res.items()}
                for a, res in self._results.items()},
            "expected": {str(a): n for a, n in self._expected.items()},
            "next_idx": {str(a): n for a, n in self._next_idx.items()},
            "completed_order": list(self._completed_order),
            "requeue_attempts": {
                str(a): n for a, n in self._requeue_attempts.items()},
            "state_masters": dict(self._state_masters),
            "state_backups": dict(self._state_backups),
            "state_epochs": dict(self._state_epochs),
            "evicted": {str(a): req.to_dict()
                        for a, req in self._evicted.items()},
            "group_hosts": {str(a): [sorted(g), sorted(h)]
                            for a, (g, h) in self._group_hosts.items()},
            "num_migrations": self._num_migrations,
            "known_hosts": sorted(set(self._hosts)
                                  or self._journal_last_hosts),
        }

    def _apply_journal_snapshot_locked(self, state: dict) -> None:
        self._in_flight = {
            int(a): (BatchExecuteRequest.from_dict(v["req"]),
                     SchedulingDecision.from_dict(v["decision"]))
            for a, v in (state.get("in_flight") or {}).items()}
        self._results = {
            int(a): {int(mid): Message.from_dict(m)
                     for mid, m in res.items()}
            for a, res in (state.get("results") or {}).items()}
        self._expected = {int(a): int(n) for a, n in
                          (state.get("expected") or {}).items()}
        self._next_idx = {int(a): int(n) for a, n in
                          (state.get("next_idx") or {}).items()}
        self._completed_order = [int(a) for a in
                                 state.get("completed_order") or []]
        self._requeue_attempts = {
            int(a): int(n) for a, n in
            (state.get("requeue_attempts") or {}).items()}
        self._state_masters = dict(state.get("state_masters") or {})
        self._state_backups = dict(state.get("state_backups") or {})
        self._state_epochs = {k: int(v) for k, v in
                              (state.get("state_epochs") or {}).items()}
        self._evicted = {int(a): BatchExecuteRequest.from_dict(r)
                         for a, r in (state.get("evicted") or {}).items()}
        self._group_hosts = {
            int(a): (set(g[0]), set(g[1]))
            for a, g in (state.get("group_hosts") or {}).items()}
        self._num_migrations = int(state.get("num_migrations") or 0)
        self._journal_last_hosts = set(state.get("known_hosts") or [])

    def _apply_journal_record_locked(self, rec: dict) -> None:
        """Apply one replayed record. Every branch is idempotent —
        applying the same record twice (compaction-crash overlap, a
        double replay in tests) must land in identical state."""
        kind = rec.get("k")
        if kind == "host_register":
            self._journal_last_hosts.add(rec["ip"])
        elif kind in ("host_remove", "host_expired"):
            self._journal_last_hosts.discard(rec["ip"])
        elif kind == "flush_hosts":
            self._journal_last_hosts.clear()
        elif kind == "app_update":
            app_id = int(rec["app_id"])
            self._expected[app_id] = int(rec.get("expected") or 0)
            if rec.get("next_idx"):
                self._next_idx[app_id] = int(rec["next_idx"])
            group = rec.get("group") or [[], []]
            gids, ghosts = self._group_hosts.get(app_id, (set(), set()))
            self._group_hosts[app_id] = (gids | set(group[0]),
                                         ghosts | set(group[1]))
            self._evicted.pop(app_id, None)
            if rec.get("req") is None:
                return
            req = BatchExecuteRequest.from_dict(rec["req"])
            decision = SchedulingDecision.from_dict(rec["decision"])
            # Prune rows whose results already replayed (idempotence:
            # a re-applied app_update must not resurrect rows that
            # earlier result records removed — those results are
            # duplicates on the second pass and would never re-remove
            # them)
            recorded = self._results.get(app_id, {})
            for mid in [m for m in decision.message_ids if m in recorded]:
                decision.remove_message(mid)
                req.messages = [m for m in req.messages if m.id != mid]
            if decision.n_messages == 0 and recorded:
                # Every row already has a result: the app is complete
                self._in_flight.pop(app_id, None)
                self._next_idx.pop(app_id, None)
                self._requeue_attempts.pop(app_id, None)
                if app_id not in self._completed_order:
                    self._completed_order.append(app_id)
                self._evict_old_results_locked()
            else:
                self._in_flight[app_id] = (req, decision)
                self._results.setdefault(app_id, {})
        elif kind == "result":
            self._record_result_locked(Message.from_dict(rec["msg"]),
                                       replay=True)
        elif kind == "app_freeze":
            app_id = int(rec["app_id"])
            self._in_flight.pop(app_id, None)
            self._evicted[app_id] = BatchExecuteRequest.from_dict(
                rec["req"])
        elif kind == "state_claim":
            self._state_masters[rec["key"]] = rec["host"]
            if "backup" in rec:
                self._state_backups[rec["key"]] = rec["backup"]
            if rec.get("epoch"):
                self._state_epochs[rec["key"]] = int(rec["epoch"])
        elif kind == "state_failover":
            self._state_masters[rec["key"]] = rec["host"]
            self._state_backups[rec["key"]] = rec.get("backup", "")
            self._state_epochs[rec["key"]] = int(rec["epoch"])
        elif kind == "state_backup":
            self._state_backups[rec["key"]] = rec.get("backup", "")
        elif kind == "state_drop":
            self._state_masters.pop(rec["key"], None)
            self._state_backups.pop(rec["key"], None)
            # epoch intentionally retained: fences a revived ex-master
        elif kind == "group":
            # Group commit (ISSUE 8): one tick's scheduling-class
            # records coalesced into one on-disk record. Atomic by the
            # record CRC — a torn tail drops the whole tick — and
            # idempotent because every sub-branch is.
            for sub in rec.get("recs") or []:
                self._apply_journal_record_locked(sub)
        elif kind == "requeued":
            pass  # forensic marker; state rides in its app_update
        elif kind == "flush_scheduling":
            self._in_flight.clear()
            self._results.clear()
            self._expected.clear()
            self._next_idx.clear()
            self._completed_order.clear()
            self._waiters.clear()
            self._requeue_attempts.clear()
            self._preloaded.clear()
        elif kind == "reset":
            self._apply_journal_snapshot_locked({})
            self._preloaded.clear()
            self._waiters.clear()
            self._next_evicted_ips.clear()
        else:
            logger.debug("Skipping unknown journal record kind %r", kind)

    def _recover_from_journal(self) -> None:
        """Restart replay: snapshot + journal → planner state, then arm
        the reconcile grace timer so decisions stranded on hosts that
        never re-register flow into the requeue machinery."""
        t0 = time.monotonic()
        snapshot, records, meta = self._journal.replay()
        if snapshot is None and not records:
            return
        with span("journal", "replay", records=len(records)):
            with self._lock:
                if snapshot is not None:
                    self._apply_journal_snapshot_locked(snapshot)
                for rec in records:
                    try:
                        self._apply_journal_record_locked(rec)
                    except Exception:  # noqa: BLE001 — one bad record
                        # must not void the rest of the recovery
                        logger.exception(
                            "Skipping unreplayable journal record %r",
                            rec.get("k"))
                in_flight_apps = len(self._in_flight)
                in_flight_msgs = sum(
                    d.n_messages for _, d in self._in_flight.values())
                n_results = sum(len(r) for r in self._results.values())
                n_masters = len(self._state_masters)
                _IN_FLIGHT_APPS.set(in_flight_apps)
                if not meta.get("snapshot_error"):
                    # Fold the replayed log immediately: a crash-restart
                    # loop must not re-apply an ever-growing journal.
                    # Skipped when the snapshot was unreadable —
                    # compacting would overwrite the corrupt file with
                    # this (partial) state and destroy any chance of
                    # manual recovery from it.
                    self._journal.compact(self._journal_snapshot_locked())
        elapsed = time.monotonic() - t0
        _JOURNAL_REPLAY_SECONDS.observe(elapsed)
        self._journal_replay_stats = {
            "records": meta.get("records", len(records)),
            "snapshot": bool(meta.get("snapshot")),
            # An unreadable snapshot means the tail records were applied
            # against EMPTY base state — a partial recovery. Loud in
            # /healthz so an operator never reads it as clean.
            "snapshotError": meta.get("snapshot_error"),
            "partial": bool(meta.get("snapshot_error")),
            "torn": bool(meta.get("torn")),
            "tornBytes": meta.get("torn_bytes", 0),
            "inFlightApps": in_flight_apps,
            "inFlightMessages": in_flight_msgs,
            "results": n_results,
            "stateMasters": n_masters,
            # concheck: ok(guard-unlocked) — __init__-time replay: the
            # planner is not yet published to any server thread
            "lastKnownHosts": sorted(self._journal_last_hosts),
            "seconds": round(elapsed, 4),
            "ts": time.time(),
        }
        logger.warning(
            "Planner replayed journal: %d record(s)%s -> %d in-flight "
            "app(s) (%d msgs), %d result(s), %d state master(s) in "
            "%.3fs", len(records),
            " + snapshot" if meta.get("snapshot") else "",
            in_flight_apps, in_flight_msgs, n_results, n_masters, elapsed)
        if meta.get("snapshot_error"):
            logger.error(
                "PARTIAL journal recovery: snapshot unreadable (%s); "
                "tail records were applied against empty base state — "
                "apps folded into the snapshot are missing",
                meta["snapshot_error"])
        flight_record("journal_replayed", records=len(records),
                      apps=in_flight_apps, messages=in_flight_msgs,
                      results=n_results, torn=bool(meta.get("torn")),
                      partial=bool(meta.get("snapshot_error")))
        flight_dump("planner_restart_replay")
        if in_flight_apps or n_masters:
            conf = get_system_config()
            grace = (conf.planner_reconcile_grace
                     or conf.planner_host_timeout)
            self._reconcile_timer = threading.Timer(
                grace, self._reconcile_after_restart)
            self._reconcile_timer.daemon = True
            self._reconcile_timer.start()
            logger.warning(
                "Reconcile armed: hosts have %.1fs to re-register "
                "before stranded decisions requeue", grace)

    def _reconcile_after_restart(self) -> None:
        """The grace window closed: in-flight rows whose host never
        re-registered go to requeue recovery; state masterships owned
        by ghosts are dropped so the next claim re-elects."""
        conf = get_system_config()
        doomed: dict[int, list[Message]] = {}
        with span("journal", "reconcile"):
            with self._lock:
                self._reconcile_timer = None
                registered = set(self._hosts)
                missing: set[str] = set()
                for app_id, (req, decision) in self._in_flight.items():
                    for i, h in enumerate(decision.hosts):
                        if h in registered:
                            continue
                        missing.add(h)
                        mid = decision.message_ids[i]
                        doomed.setdefault(app_id, []).extend(
                            m for m in req.messages if m.id == mid)
                ghosts = {v for v in self._state_masters.values()
                          if v not in registered}
                if ghosts:
                    self._drop_state_masters_for_locked(ghosts)
        n_msgs = sum(len(v) for v in doomed.values())
        self._reconcile_stats = {
            "ts": time.time(),
            "graceSeconds": (conf.planner_reconcile_grace
                             or conf.planner_host_timeout),
            "missingHosts": sorted(missing),
            "requeuedApps": len(doomed),
            "requeuedMessages": n_msgs,
            "droppedStateMasters": len(ghosts),
        }
        flight_record("planner_reconcile", apps=len(doomed),
                      messages=n_msgs, missing_hosts=sorted(missing))
        if not doomed:
            logger.info("Reconcile after restart: every replayed host "
                        "re-registered; nothing to requeue")
            return
        _RECONCILED_MESSAGES.inc(n_msgs)
        logger.warning(
            "Reconcile after restart: host(s) %s never re-registered; "
            "requeueing %d message(s) across %d app(s)",
            sorted(missing), n_msgs, len(doomed))
        for app_id, msgs in doomed.items():
            threading.Thread(
                target=self._recover_messages,
                args=(app_id, msgs,
                      b"Host never re-registered after planner restart"),
                name=f"planner/recover@{app_id}", daemon=True).start()

    def _reclaim_host_rows_locked(self, ip: str) -> None:
        """Re-apply slot/port/device claims for in-flight rows pinned to
        a freshly (re)created host record — a new PlannerHost starts at
        zero used slots, which would otherwise double-book capacity
        under replayed (or rejoin-racing-recovery) decisions."""
        host = self._hosts.get(ip)
        if host is None or not self._in_flight:
            return
        n = 0
        for _, (_, decision) in self._in_flight.items():
            for i, h in enumerate(decision.hosts):
                if h != ip:
                    continue
                host.state.claim(1)
                if decision.mpi_ports[i]:
                    host.used_mpi_ports.add(decision.mpi_ports[i])
                dev = decision.device_ids[i]
                if 0 <= dev < len(host.device_load):
                    host.device_load[dev] += 1
                n += 1
        if n:
            logger.info("Re-claimed %d in-flight slot(s) on "
                        "(re)registered host %s", n, ip)

    def flush_journal(self) -> None:
        """fsync any batched journal writes (server stop path)."""
        self._journal.flush()

    def close_journal(self) -> None:
        """Drain + fsync + close the journal (fd and drain thread).
        The lifecycle hook for clean shutdown and in-process
        start/stop cycles; reopening requires a new Planner."""
        with self._lock:
            if self._reconcile_timer is not None:
                self._reconcile_timer.cancel()
                self._reconcile_timer = None
        self._journal.close()

    # ------------------------------------------------------------------
    # Observability / reset
    # ------------------------------------------------------------------
    def get_num_migrations(self) -> int:
        with self._lock:
            return self._num_migrations

    def get_in_flight_apps(self) -> dict[int, SchedulingDecision]:
        with self._lock:
            return {app: d for app, (_, d) in self._in_flight.items()}

    def in_flight_summary(self) -> dict:
        """Observability snapshot for the REST surface (reference
        GetInFlightAppsResponse, planner.proto:69-89)."""
        with self._lock:
            apps = [{
                "appId": app_id,
                "subType": req.subtype,
                "size": decision.n_messages,
                "hostIps": decision.unique_hosts(),
            } for app_id, (req, decision) in self._in_flight.items()]
            frozen = [{"appId": app_id, "subType": req.subtype,
                       "size": req.n_messages()}
                      for app_id, req in self._evicted.items()]
            evicted_ips = sorted(self._next_evicted_ips)
            n_migrations = self._num_migrations
        return {
            "apps": apps,
            "numMigrations": n_migrations,
            "nextEvictedVmIps": evicted_ips,
            "frozenApps": frozen,
        }

    def health_summary(self) -> dict:
        """Aggregate liveness view behind the planner's ``GET /healthz``:
        per registered host the last keep-alive age and this planner's
        circuit-breaker state toward it, plus in-flight counts. Built
        entirely from planner-local state — a health probe must never
        block on the workers it is asking about."""
        conf = get_system_config()
        now = time.monotonic()
        with self._lock:
            hosts = [{
                "host": ip,
                "slots": h.state.slots,
                "usedSlots": h.state.used_slots,
                "keepAliveAgeSeconds": round(now - h.register_ts, 3),
                "timeoutSeconds": conf.planner_host_timeout,
            } for ip, h in self._hosts.items()]
            in_flight_apps = len(self._in_flight)
            in_flight_messages = sum(
                d.n_messages for _, d in self._in_flight.values())
            results_total = self._results_count
            results_failed = self._results_failed
        # Breaker states live on the pooled dispatch clients; a host with
        # no client yet simply has no breaker row
        breakers = {}
        for ip, client in self._clients.items():
            b = getattr(client, "breaker", None)
            if b is not None:
                # .state/.failures, NOT .allow(): allow() consumes the
                # half-open trial slot — a health probe must never eat
                # the one attempt that would have closed the breaker
                breakers[ip] = {
                    "state": b.state,
                    "consecutiveFailures": b.failures,
                }
        for row in hosts:
            row["breaker"] = breakers.get(row["host"])
        # Journal lag: size, last-fsync age and the latest replay/
        # reconcile stats — the probe a supervisor watches to know the
        # restarted planner actually recovered (acceptance: recovery
        # visible in /healthz)
        journal = self._journal.stats()
        if self._journal_replay_stats is not None:
            journal["lastReplay"] = self._journal_replay_stats
        if self._reconcile_stats is not None:
            journal["lastReconcile"] = self._reconcile_stats
        from faabric_tpu.batch_scheduler import get_decision_cache

        # ISSUE 12 satellite: the perf block — local profile-store
        # cardinality, cluster straggler counts from the last /perf
        # aggregation, and that aggregation's age (None = never ran).
        # Planner-local state only, like everything else here.
        from faabric_tpu.telemetry import (
            get_collective_profiler,
            get_perf_store,
        )

        agg = self._perf_agg_stats
        perf_block = {
            "profileLinksLocal": get_perf_store().cardinality(),
            "stragglersLocal": len(get_collective_profiler().detect()),
            "lastAggregationAgeSeconds": (
                round(now - agg["at"], 3) if agg else None),
            "clusterLinks": agg["links"] if agg else None,
            "clusterStragglers": agg["stragglers"] if agg else None,
        }

        # ISSUE 14: the lifecycle digest (per-phase quantiles + the
        # dominant-phase ranking) and the SLO burn status — what the
        # doctor and a high-QPS driver read instead of inferring from
        # point-in-time counters
        from faabric_tpu.telemetry import (
            get_lifecycle_stats,
            get_slo_tracker,
        )

        return {
            "status": "ok",
            "hosts": hosts,
            "inFlightApps": in_flight_apps,
            "inFlightMessages": in_flight_messages,
            "resultsTotal": results_total,
            "resultsFailed": results_failed,
            "lifecycle": get_lifecycle_stats().snapshot(),
            "slo": get_slo_tracker().status(),
            "perf": perf_block,
            # ISSUE 8 satellite: admission-queue depth/shed, tick
            # occupancy and the decision-cache hit rate, so an operator
            # can see the ingress breathe under load
            "ingress": self.ingress.stats(),
            "decisionCache": get_decision_cache().stats(),
            "journal": journal,
        }

    # -- time-series gauges (ISSUE 14): cheap accessors the sampler
    # polls at ~1 Hz — each is one lock acquisition over dict sums -----
    def free_slot_watermark(self) -> int:
        with self._lock:
            return sum(max(0, h.state.slots - h.state.used_slots)
                       for h in self._hosts.values())

    def result_backlog(self) -> int:
        """Outstanding result waits registered with the planner."""
        with self._lock:
            return len(self._waiters)

    def in_flight_message_count(self) -> int:
        with self._lock:
            return sum(d.n_messages for _, d in self._in_flight.values())

    def results_total(self) -> int:
        with self._lock:
            return self._results_count

    def note_perf_aggregation(self, doc: dict) -> None:
        """Record the summary of a completed ``/perf`` aggregation
        (endpoint-driven): healthz reports its age and headline counts
        so the doctor can tell a stale profile from a fresh one."""
        self._perf_agg_stats = {
            "at": time.monotonic(),
            "links": len(doc.get("links") or []),
            "stragglers": len(doc.get("stragglers") or []),
        }

    def collect_telemetry(self, include_trace: bool = False,
                          timeout: float = 5.0,
                          blocks: tuple[str, ...] | None = None) -> dict:
        """host label → {"metrics": snapshot, "trace": [events]} from this
        (planner) process plus every registered worker's local registry —
        the aggregation behind ``GET /metrics`` and ``GET /trace``.
        Workers are scraped CONCURRENTLY under one deadline: a host that
        fails — or is wedged past ``timeout`` — is skipped, not fatal; a
        scrape must not go down (or block a Prometheus scrape window)
        with one bad host. ``blocks`` narrows both the planner's own
        entry and the worker RPCs to the named blocks (the /timeseries
        trend poll asks for just its ring, not the full payload)."""
        from faabric_tpu.telemetry import (
            get_comm_matrix,
            get_lifecycle_stats,
            get_proc_stats,
            get_timeseries,
            perf_telemetry_block,
            profile_telemetry_block,
            statestats_telemetry_block,
            trace_events,
        )

        # Fresh process gauges on every scrape, sampler or not
        get_proc_stats().refresh()
        from faabric_tpu.device_plane.plane import device_planes_summary

        builders = {
            "metrics": lambda: get_metrics().snapshot(),
            "commmatrix": lambda: get_comm_matrix().snapshot(),
            "perf": perf_telemetry_block,
            "lifecycle": lambda: get_lifecycle_stats().snapshot(),
            "timeseries": lambda: get_timeseries().snapshot(),
            # ISSUE 15: live device-plane summaries (executable-cache
            # stats, copy accounting) — GET /topology's device block
            "device_planes": device_planes_summary,
            # ISSUE 16: per-key state access ledger + snapshot lifecycle
            # stats — GET /statemap merges these across hosts
            "statestats": statestats_telemetry_block,
            # ISSUE 18: in-process sampling profiler trie + GIL gauge —
            # GET /profile merges these across hosts
            "profile": profile_telemetry_block,
        }
        out: dict = {"planner": {name: build() for name, build in
                                 builders.items()
                                 if blocks is None or name in blocks}}
        if include_trace:
            out["planner"]["trace"] = trace_events()

        # One in-flight scrape per host, ever: a wedged host's thread can
        # block inside its client's sync RPC for the full socket timeout,
        # and each scrape holds that client's sync lock — spawning a new
        # thread per GET while the old one is stuck would pile threads up
        # behind the lock without bound. A host with a live scrape is
        # simply absent from this response.
        ips = [h.ip for h in self.get_available_hosts()]
        slots: list = [None] * len(ips)  # per-thread slot: a straggler
        # writing after the deadline mutates only its own cell, never the
        # dict the caller is iterating

        def scrape(i: int, ip: str) -> None:
            try:
                slots[i] = self._get_client(ip).get_telemetry(
                    include_trace, blocks=blocks)
            except Exception:  # noqa: BLE001
                logger.warning("Telemetry scrape of %s failed", ip)
            finally:
                self._telemetry_scrapes.pop(ip, None)

        threads = []
        for i, ip in enumerate(ips):
            t = threading.Thread(target=scrape, args=(i, ip),
                                 name=f"telemetry/scrape@{ip}",
                                 daemon=True)
            if self._telemetry_scrapes.setdefault(ip, t) is not t:
                logger.warning(
                    "Skipping telemetry scrape of %s (previous scrape "
                    "still in flight)", ip)
                continue
            try:
                t.start()
            except RuntimeError:  # thread/fd exhaustion: don't leave the
                # registration behind or the host is skipped forever
                self._telemetry_scrapes.pop(ip, None)
                logger.warning("Could not start telemetry scrape of %s", ip)
                continue
            threads.append(t)
        deadline = time.monotonic() + timeout
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
        for ip, tel in zip(ips, slots):
            if tel is not None:
                out[ip] = tel
        return out

    def flush_hosts(self) -> None:
        with self._lock:
            if self._journal.enabled:
                self._journal_append("flush_hosts")
            self._hosts.clear()

    def flush_all_executors(self) -> list[str]:
        """Broadcast a flush to every registered worker; returns the hosts
        flushed."""
        hosts = [h.ip for h in self.get_available_hosts()]
        for ip in hosts:
            try:
                self._get_client(ip).send_flush()
            except Exception:  # noqa: BLE001
                logger.exception("Flush of %s failed", ip)
        return hosts

    def get_frozen_apps(self) -> list[int]:
        with self._lock:
            return list(self._evicted)

    def num_registered_hosts(self) -> int:
        with self._lock:
            return len(self._hosts)

    def reset(self) -> None:
        with self._lock:
            if self._reconcile_timer is not None:
                self._reconcile_timer.cancel()
                self._reconcile_timer = None
            if self._journal.enabled:
                # A reset is itself a durable mutation: without the
                # record, a replay would resurrect pre-reset state
                self._journal_append("reset")
            self._hosts.clear()
            self._in_flight.clear()
            self._results.clear()
            self._expected.clear()
            self._next_idx.clear()
            self._completed_order.clear()
            self._waiters.clear()
            self._requeue_attempts.clear()
            self._preloaded.clear()
            self._evicted.clear()
            self._next_evicted_ips.clear()
            self._group_hosts.clear()
            self._state_masters.clear()
            self._state_backups.clear()
            self._state_epochs.clear()
            self._device_plane = {"roster": [], "size": 0, "port": 0}
            self._num_migrations = 0
            self._clients.close_all()
            self._snapshot_clients.close_all()
            _IN_FLIGHT_APPS.set(0)
        from faabric_tpu.batch_scheduler import get_decision_cache
        from faabric_tpu.transport.ptp_remote import close_mapping_clients

        get_decision_cache().clear()
        close_mapping_clients()
        # AFTER the wipe: shed_all records terminal FAILED results for
        # fire-and-forget submissions still queued at reset time — done
        # before the wipe those results would be erased and their
        # batch-status pollers would hang forever
        self.ingress.shed_all("planner reset")

    def flush_scheduling_state(self) -> None:
        with self._lock:
            if self._journal.enabled:
                self._journal_append("flush_scheduling")
            self._in_flight.clear()
            _IN_FLIGHT_APPS.set(0)
            self._results.clear()
            self._expected.clear()
            self._next_idx.clear()
            self._completed_order.clear()
            self._waiters.clear()
            self._requeue_attempts.clear()
            self._preloaded.clear()
            for h in self._hosts.values():
                h.state.used_slots = 0
                h.used_mpi_ports.clear()
                h.device_load = [0] * len(h.device_load)
        from faabric_tpu.batch_scheduler import get_decision_cache

        get_decision_cache().clear()


_planner: Optional[Planner] = None
_planner_lock = threading.Lock()


def get_planner() -> Planner:
    global _planner
    if _planner is None:
        with _planner_lock:
            if _planner is None:
                _planner = Planner()
    return _planner
