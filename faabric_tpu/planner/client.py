"""Per-host planner client.

Reference analog: src/planner/PlannerClient.cpp (429 lines) — including the
blocking getMessageResult with a local promise cache (the planner registers
the host's interest and pushes the result to the host's FunctionCallServer,
which resolves the promise; :202-270), callFunctions (:283-370) and the
KeepAliveThread re-registering the host every half-timeout
(PlannerClient.h:21-33).

Mock mode records batch calls / results instead of sending.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from faabric_tpu.batch_scheduler.decision import SchedulingDecision
from faabric_tpu.faults import SUPPRESS, fault_point, faults_enabled
from faabric_tpu.planner.server import PlannerCalls
from faabric_tpu.proto import (
    BatchExecuteRequest,
    BatchExecuteRequestStatus,
    BatchExecuteType,
    Message,
    ber_to_wire,
    get_main_thread_snapshot_key,
    messages_from_wire,
    messages_to_wire,
)
from faabric_tpu.telemetry import flight_record, get_lifecycle, get_metrics
from faabric_tpu.telemetry.lifecycle import (
    PHASE_RESULT_PUSH,
    PHASE_WAITER_WAKE,
)
from faabric_tpu.transport.client import MessageEndpointClient, RpcError
from faabric_tpu.transport.common import PLANNER_ASYNC_PORT, PLANNER_SYNC_PORT
from faabric_tpu.util.config import get_system_config
from faabric_tpu.util.logging import get_logger
from faabric_tpu.util.periodic import PeriodicBackgroundThread
from faabric_tpu.util.testing import is_mock_mode

logger = get_logger(__name__)

_FAULTS = faults_enabled()
_FP_KEEPALIVE = fault_point("keepalive")

_LC = get_lifecycle()

_metrics = get_metrics()
_BUFFERED_RESULTS = _metrics.counter(
    "faabric_planner_client_buffered_results_total",
    "Results queued locally because the planner was unreachable")
_FLUSHED_RESULTS = _metrics.counter(
    "faabric_planner_client_flushed_results_total",
    "Buffered results delivered to the planner after reconnect")
_DROPPED_RESULTS = _metrics.counter(
    "faabric_planner_client_dropped_results_total",
    "Buffered results dropped because the outage queue overflowed")

# ---------------------------------------------------------------------------
# Mock recording
# ---------------------------------------------------------------------------
_mock_lock = threading.Lock()
_mock_batch_calls: list[BatchExecuteRequest] = []
_mock_results: list[Message] = []


def get_mock_batch_calls() -> list[BatchExecuteRequest]:
    with _mock_lock:
        return list(_mock_batch_calls)


def get_mock_set_results() -> list[Message]:
    with _mock_lock:
        return list(_mock_results)


def clear_mock_planner_calls() -> None:
    with _mock_lock:
        _mock_batch_calls.clear()
        _mock_results.clear()


class KeepAliveThread(PeriodicBackgroundThread):
    thread_name = "runtime/keep-alive"

    def __init__(self, client: "PlannerClient", slots: int, n_devices: int) -> None:
        super().__init__()
        self.client = client
        self.slots = slots
        self.n_devices = n_devices

    def do_work(self) -> None:
        if _FAULTS and _FP_KEEPALIVE.fire(
                host=self.client.this_host) is SUPPRESS:
            # Injected keep-alive loss: the planner expires this (alive)
            # host — the chaos recipe for exercising expiry recovery and
            # the rejoin path without killing a process
            return
        try:
            self.client.register_host(self.slots, self.n_devices,
                                      rejoin=True)
        except RpcError as e:
            # Planner down/restarting (ISSUE 4 satellite): never raise
            # out of the keep-alive thread and never spin — the periodic
            # interval paces the retries, the client's circuit breaker
            # makes each failed tick instant while open, and the
            # breaker's half-open probe adds the jitter. Log once per
            # outage, not per tick.
            if not self.client.planner_down:
                logger.warning(
                    "Planner unreachable from %s (%s); keep-alive will "
                    "keep retrying and results will buffer locally",
                    self.client.this_host, e)
                flight_record("planner_unreachable",
                              host=self.client.this_host)
            self.client.planner_down = True
            return
        if self.client.planner_down:
            self.client.planner_down = False
            logger.warning("Planner reachable again from %s; draining "
                           "buffered results", self.client.this_host)
            flight_record("planner_reconnected",
                          host=self.client.this_host)
            # A blip means any recently async-pushed result is suspect:
            # the FIRST write on a connection whose peer just died
            # "succeeds" into the kernel buffer and is silently lost
            # (only the next write errors). Re-deliver the window; the
            # planner's first-write-wins dedups the ones that landed.
            self.client.requeue_recent_results()
            # The planner behind the blip may be a restarted one whose
            # waiter map is gone; one resync round after an outage is
            # cheap and covers it even when journal replay keeps us
            # "known" and the boot check races the first tick.
            self.client._resync_all = True
        # Reconnect housekeeping: the flush is a no-op check while
        # nothing is pending; the resync (one sync RPC per covered
        # wait) only runs while a restart/rejoin signal or a blocked
        # waiter's lost-push nudge is live — it consumes the signals
        # itself and keeps them on an RpcError-cut round.
        self.client.flush_pending_results()
        if self.client._resync_all or self.client._resync_nudged:
            self.client.resync_result_interest()


class PlannerClient(MessageEndpointClient):
    """One per worker runtime, carrying the worker's host identity."""

    # Concurrency contract (tools/concheck.py): waiter machinery under
    # _results_lock, outage buffers under _pending_lock. Deliberately
    # unlisted: planner_down and _resync_all are set-only signal flags
    # whose races are benign (consumed under _results_lock /
    # re-checked by the keep-alive tick); _planner_boot is only touched
    # from the keep-alive thread; _keep_alive and the snapshot-client
    # handles are start/stop sequenced by the runtime.
    GUARDS = {
        "_local_results": "_results_lock",
        "_local_results_order": "_results_lock",
        "_result_events": "_results_lock",
        "_result_waiters": "_results_lock",
        "_result_interest": "_results_lock",
        "_resync_nudged": "_results_lock",
        "_pending_results": "_pending_lock",
        "_pending_bytes": "_pending_lock",
        "_recent_results": "_pending_lock",
        "_recent_bytes": "_pending_lock",
        "_out_results": "_pending_lock",
        "_out_sending": "_pending_lock",
    }

    def __init__(self, this_host: str = "",
                 planner_host: str | None = None) -> None:
        conf = get_system_config()
        super().__init__(planner_host or conf.planner_host,
                         PLANNER_ASYNC_PORT, PLANNER_SYNC_PORT)
        self.this_host = this_host
        self._keep_alive: Optional[KeepAliveThread] = None

        # Set by the WorkerRuntime; used to push main-thread snapshots to
        # the planner ahead of THREADS batches
        self.snapshot_registry = None
        self._planner_snapshot_client = None

        # Local result promises: msg_id → Event; results land either via the
        # planner's push to our FunctionCallServer or via a direct response.
        # The cache is bounded (oldest-first) — a long-lived worker must not
        # accumulate one Message per completed invocation forever.
        self._results_lock = threading.Lock()
        self._local_results: dict[int, Message] = {}
        self._local_results_order: list[int] = []
        self._result_events: dict[int, threading.Event] = {}
        # msg_id → number of threads blocked on that Event: the entry
        # (and the planner-side interest) unwinds only when the LAST
        # waiter gives up, never when one of several times out or hits
        # an RpcError
        self._result_waiters: dict[int, int] = {}
        # msg_id → app_id for every outstanding wait: a restarted
        # planner lost its waiter map, so after rejoin the keep-alive
        # re-registers this host's interest (resync_result_interest)
        self._result_interest: dict[int, int] = {}

        # Degraded mode (ISSUE 4): results the planner could not be
        # told about (down/restarting) queue here and drain through the
        # sync FLUSH_RESULTS call after reconnect — a planner outage
        # must not raise into executors or lose completed work
        self._pending_lock = threading.Lock()
        self._pending_results: list[Message] = []
        self._pending_bytes = 0
        self._recent_bytes = 0
        # Result coalescing (ISSUE 8): results that arrive while a push
        # RPC is already in flight queue here and ride the NEXT push as
        # one batched frame — group commit by contention. Zero added
        # latency when idle (an uncontended result sends inline exactly
        # as before); at high QPS the result plane automatically
        # batches instead of paying one RPC per result.
        self._out_results: list[Message] = []
        self._out_sending = False
        # Recently async-pushed results (bounded by count AND age): a
        # result written into the kernel buffer of a connection whose
        # planner just died is silently lost — the send "succeeds", the
        # restarted planner never sees it, and nothing ever re-sends it
        # (the host is alive, so reconcile won't requeue). On rejoin
        # (known:false — the planner restarted or expired us) the
        # recent window re-delivers through the confirmed sync flush;
        # the planner's first-write-wins dedups the common case where
        # the push did land.
        self._recent_results: list[tuple[float, Message]] = []
        self.planner_down = False
        # Planner incarnation last seen in a register/keep-alive
        # response, and what the next resync round owes.
        # resync_result_interest costs one sync RPC per covered wait,
        # so it only runs when a signal fires — _resync_all for the
        # three restart signals (boot change, known:false rejoin,
        # outage recovery; the whole waiter map died), _resync_nudged
        # for blocked waiters' lost-push nudges (only those ids are
        # re-polled, so one long-running wait does not put every other
        # wait back on the per-tick poll this gating removed).
        self._planner_boot: str | None = None
        self._resync_all = False
        self._resync_nudged: set[int] = set()

    MAX_CACHED_RESULTS = 10_000
    # Both outage buffers are bounded by count AND payload bytes — a
    # worker returning multi-MB outputs through a long outage must not
    # OOM before the count cap bites
    MAX_PENDING_RESULTS = 10_000
    MAX_PENDING_BYTES = 256 << 20
    MAX_RECENT_RESULTS = 512
    MAX_RECENT_BYTES = 64 << 20
    RECENT_RESULT_WINDOW = 60.0

    @staticmethod
    def _result_cost(msg: Message) -> int:
        """Approximate retained bytes of a buffered result."""
        return len(msg.output_data) + len(msg.input_data) + 512

    # ------------------------------------------------------------------
    def ping(self) -> bool:
        resp = self.sync_send(int(PlannerCalls.PING), idempotent=True)
        return bool(resp.header.get("pong"))

    def register_host(self, slots: int, n_devices: int = 0,
                      overwrite: bool = False, start_keep_alive: bool = False,
                      rejoin: bool = False) -> float:
        resp = self.sync_send(int(PlannerCalls.REGISTER_HOST), {
            "host": self.this_host, "slots": slots,
            "n_devices": n_devices, "overwrite": overwrite,
        }, idempotent=True)
        timeout = float(resp.header.get("host_timeout", 30.0))
        boot = resp.header.get("boot")
        if boot is not None:
            if self._planner_boot is not None and boot != self._planner_boot:
                # The planner restarted between ticks and its journal
                # replay re-registered us, so known stays True and no
                # tick ever failed — but the restart still dropped the
                # in-memory waiter map and any result write that died
                # in the old incarnation's socket buffer.
                logger.warning(
                    "Planner incarnation changed under %s; re-delivering "
                    "recent results and re-registering waiter interest",
                    self.this_host)
                self.requeue_recent_results()
                self._resync_all = True
            self._planner_boot = boot
        if rejoin and not overwrite and not resp.header.get("known", True):
            # Keep-alive found us UNKNOWN to the planner: we expired off
            # the registry (paused past the timeout, partitioned, or the
            # planner restarted) while staying alive. Re-register with
            # overwrite=True so the planner treats this as a boot and
            # drops any pooled connections to our assumed-dead
            # incarnation — otherwise we stay invisible forever while
            # dutifully keep-aliving a registry entry that isn't there.
            logger.warning(
                "Host %s was expired/unknown at the planner; rejoining",
                self.this_host)
            self.sync_send(int(PlannerCalls.REGISTER_HOST), {
                "host": self.this_host, "slots": slots,
                "n_devices": n_devices, "overwrite": True,
            }, idempotent=True)
            # The planner forgot us: it restarted (journal replay keeps
            # results it RECEIVED, not ones that died in a socket
            # buffer) or expired us. Re-deliver the recent result
            # window via the confirmed flush; first-write-wins dedups.
            self.requeue_recent_results()
            self._resync_all = True
        if start_keep_alive and self._keep_alive is None:
            self._keep_alive = KeepAliveThread(self, slots, n_devices)
            self._keep_alive.start(max(0.5, timeout / 2))
        return timeout

    def remove_host(self) -> None:
        if self._keep_alive is not None:
            self._keep_alive.stop()
            self._keep_alive = None
        try:
            # Last chance to deliver results completed during an outage
            # before this host deregisters
            self.flush_pending_results()
            self.sync_send(int(PlannerCalls.REMOVE_HOST),
                           {"host": self.this_host}, idempotent=True)
        except RpcError as e:
            # Best-effort by contract (ISSUE 4 satellite): a worker
            # shutting down while the planner is down must not raise or
            # retry-spin — the planner's keep-alive expiry reaps the
            # registration anyway
            logger.debug("Best-effort deregister of %s skipped: %s",
                         self.this_host, e)

    def get_available_hosts(self) -> list[dict]:
        resp = self.sync_send(int(PlannerCalls.GET_AVAILABLE_HOSTS),
                              idempotent=True)
        return resp.header.get("hosts", [])

    # ------------------------------------------------------------------
    def call_functions(self, req: BatchExecuteRequest) -> SchedulingDecision:
        """Invoke a batch through the planner (reference callFunctions)."""
        if is_mock_mode():
            with _mock_lock:
                _mock_batch_calls.append(req)
            return SchedulingDecision(req.app_id, req.group_id)

        # THREADS batches set the main host and push the main-thread
        # snapshot to the planner once per key (reference
        # PlannerClient.cpp:283-370 and its pushedSnapshots cache).
        if req.type == int(BatchExecuteType.THREADS) and req.messages:
            for m in req.messages:
                m.main_host = self.this_host
            if not req.snapshot_key:
                req.snapshot_key = get_main_thread_snapshot_key(req.messages[0])
            if self.snapshot_registry is not None:
                snap = self.snapshot_registry.try_get_snapshot(req.snapshot_key)
                if snap is not None:
                    # Always push the full current image: a repeated batch
                    # on the same key must not leave the planner holding a
                    # stale pre-merge copy. (The reference optimises the
                    # repeat case with pushSnapshotUpdate diffs — a future
                    # optimisation here; correctness first.)
                    from faabric_tpu.snapshot.remote import SnapshotClient

                    if self._planner_snapshot_client is None:
                        self._planner_snapshot_client = SnapshotClient(self.host)
                    self._planner_snapshot_client.push_snapshot(
                        req.snapshot_key, snap)

        header, tail = ber_to_wire(req)
        # The host identity keys per-source admission credits on the
        # ingress — without it every sync caller would share one
        # anonymous credit bucket
        resp = self.sync_send(int(PlannerCalls.CALL_BATCH),
                              {"ber": header, "host": self.this_host},
                              tail)
        return SchedulingDecision.from_dict(resp.header["decision"])

    def submit_functions(self, req: BatchExecuteRequest
                         ) -> tuple[bool, float]:
        """High-QPS submission (ISSUE 8): enqueue the batch into the
        planner's ingress and return ``(accepted, retry_after)``
        immediately — no scheduling decision in the response. The
        planner's tick batches admitted invocations; results arrive
        through the normal result plane (``get_batch_results`` /
        ``get_message_result``). ``accepted=False`` means admission
        shed the batch — back off ``retry_after`` seconds and retry."""
        return self.submit_functions_many([req])

    def submit_functions_many(self, reqs: list[BatchExecuteRequest]
                              ) -> tuple[bool, float]:
        """Bulk high-QPS submission: many INDEPENDENT apps in one RPC
        (the client-side analog of the planner's pipelined dispatch —
        at thousands of invocations per second, one sync round-trip per
        invocation is the client's dominant cost). Admission is
        all-or-nothing for the bulk: size submissions modestly and back
        off ``retry_after`` on a shed."""
        if not reqs:
            return True, 0.0
        if is_mock_mode():
            with _mock_lock:
                _mock_batch_calls.extend(reqs)
            return True, 0.0
        from faabric_tpu.proto import bers_to_wire

        header, tail = bers_to_wire(reqs)
        header["host"] = self.this_host
        resp = self.sync_send(int(PlannerCalls.SUBMIT_BATCH), header,
                              tail)
        return (bool(resp.header.get("accepted")),
                float(resp.header.get("retry_after", 0.0)))

    # ------------------------------------------------------------------
    def set_message_result(self, msg: Message) -> None:
        if is_mock_mode():
            with _mock_lock:
                _mock_results.append(msg)
            return
        # Lifecycle ledger (ISSUE 14): the worker is about to push the
        # result — last stamp taken on this host's side of the wire
        _LC.stamp(msg, PHASE_RESULT_PUSH)
        # Earlier buffered results go first so the planner sees results
        # in completion order (first-write-wins makes reordering safe,
        # but ordered delivery keeps forensics sane)
        # concheck: ok(guard-unlocked) — racy emptiness probe by design:
        # flush_pending_results re-checks under _pending_lock, so a torn
        # read only costs one early/late flush attempt
        if self._pending_results:
            self.flush_pending_results()
        with self._pending_lock:
            self._out_results.append(msg)
            if self._out_sending:
                # Another thread's push RPC is in flight; it drains the
                # queue when it finishes — this result rides the next
                # frame (coalesced result plane, ISSUE 8)
                return
            self._out_sending = True
        self._drain_out_results()

    def _drain_out_results(self) -> None:
        """Owner loop of the coalesced result plane: send whatever has
        accumulated as ONE batched push, and keep going until the queue
        is empty (results that landed during the send ride the next
        frame). Exactly one thread owns this loop at a time
        (_out_sending) — which is why EVERY exit path, including an
        unexpected exception, must clear the flag: a wedged True would
        silently park every future result on this worker forever."""
        try:
            while True:
                with self._pending_lock:
                    batch = self._out_results
                    self._out_results = []
                    if not batch:
                        self._out_sending = False
                        return
                try:
                    dicts, tail = messages_to_wire(batch)
                    header = ({"msg": dicts[0]} if len(dicts) == 1
                              else {"msgs": dicts})
                    retried = self.async_send(
                        int(PlannerCalls.SET_MESSAGE_RESULT), header, tail)
                except RpcError:
                    for m in batch:
                        self._buffer_result(m)
                    continue
                except Exception:  # noqa: BLE001 — one poison message
                    # (unencodable field) must not sink the batch, and
                    # must not wedge the drain loop: retry each result
                    # alone, dropping only the poison (matches the
                    # pre-coalescing behavior where the bad message
                    # raised out of its own push and was lost alone)
                    logger.exception(
                        "Batched result push from %s failed; retrying "
                        "the %d result(s) individually", self.this_host,
                        len(batch))
                    self._push_results_individually(batch)
                    continue
                with self._pending_lock:
                    for m in batch:
                        self._remember_result_locked(m)
                if retried:
                    # The frame only went out after a reconnect: an
                    # EARLIER result pushed on the old connection may
                    # have died in the old peer's kernel buffer (that
                    # write "succeeded"; only this one saw the error).
                    # Re-deliver the recent window through the confirmed
                    # flush — the planner's first-write-wins dedups
                    # everything that did land.
                    logger.warning(
                        "Result push from %s needed a reconnect; "
                        "re-delivering the recent result window",
                        self.this_host)
                    self.requeue_recent_results()
                    self.flush_pending_results()
        except BaseException:
            # Abnormal exit (should be unreachable — kept so the
            # ownership flag can never stay latched)
            with self._pending_lock:
                self._out_sending = False
            raise

    def _push_results_individually(self, batch: list[Message]) -> None:
        """Fallback for a failed coalesced frame: one push per result so
        only the genuinely unsendable message is dropped."""
        for m in batch:
            try:
                dicts, tail = messages_to_wire([m])
                self.async_send(int(PlannerCalls.SET_MESSAGE_RESULT),
                                {"msg": dicts[0]}, tail)
            except RpcError:
                self._buffer_result(m)
            except Exception:  # noqa: BLE001
                logger.exception("Dropping unsendable result %d from %s",
                                 m.id, self.this_host)
            else:
                with self._pending_lock:
                    self._remember_result_locked(m)

    def _remember_result_locked(self, msg: Message) -> None:
        now = time.monotonic()
        recent = self._recent_results
        recent.append((now, msg))
        self._recent_bytes += self._result_cost(msg)
        cutoff = now - self.RECENT_RESULT_WINDOW
        while recent and (recent[0][0] < cutoff
                          or len(recent) > self.MAX_RECENT_RESULTS
                          or self._recent_bytes > self.MAX_RECENT_BYTES):
            self._recent_bytes -= self._result_cost(recent.pop(0)[1])

    def requeue_recent_results(self) -> None:
        """Move the recent-results window onto the pending queue (next
        flush re-delivers it). Called after a rejoin: the planner we
        pushed those results to may have died with them in a kernel
        buffer."""
        with self._pending_lock:
            if not self._recent_results:
                return
            have = {m.id for m in self._pending_results}
            resend = [m for _, m in self._recent_results
                      if m.id not in have]
            self._pending_results[:0] = resend
            self._pending_bytes += sum(self._result_cost(m)
                                       for m in resend)
            self._recent_results.clear()
            self._recent_bytes = 0
            n = len(resend)
        if n:
            logger.info(
                "Re-delivering %d recently pushed result(s) from %s "
                "after rejoin (planner restart may have dropped them)",
                n, self.this_host)

    def _buffer_result(self, msg: Message) -> None:
        """Queue a result the planner could not be reached for; the
        queue drains on reconnect (keep-alive) or the next successful
        result push. Bounded drop-oldest: a long outage must not OOM a
        busy worker."""
        with self._pending_lock:
            pending = self._pending_results
            pending.append(msg)
            self._pending_bytes += self._result_cost(msg)
            dropped = 0
            while pending and (len(pending) > self.MAX_PENDING_RESULTS
                               or self._pending_bytes
                               > self.MAX_PENDING_BYTES):
                self._pending_bytes -= self._result_cost(pending.pop(0))
                dropped += 1
            if dropped:
                _DROPPED_RESULTS.inc(dropped)
                logger.warning(
                    "Outage result queue overflowed on %s; dropped %d "
                    "oldest result(s)", self.this_host, dropped)
            n = len(pending)
        _BUFFERED_RESULTS.inc()
        if not self.planner_down:
            self.planner_down = True
            logger.warning(
                "Planner unreachable from %s; buffering results "
                "locally (%d queued)", self.this_host, n)
            flight_record("planner_unreachable", host=self.this_host)

    def flush_pending_results(self) -> None:
        """Deliver queued results through the sync FLUSH_RESULTS call
        (delivery-confirmed, unlike the async push) and clear the queue.
        Failure re-queues everything untouched — called again on the
        next keep-alive tick."""
        with self._pending_lock:
            if not self._pending_results:
                return
            batch = self._pending_results
            self._pending_results = []
            self._pending_bytes = 0
        try:
            dicts, tail = messages_to_wire(batch)
            resp = self.sync_send(int(PlannerCalls.FLUSH_RESULTS),
                                  {"msgs": dicts, "host": self.this_host},
                                  tail, idempotent=True)
            accepted = int(resp.header.get("accepted", len(batch)))
            _FLUSHED_RESULTS.inc(accepted)
            logger.info("Flushed %d buffered result(s) from %s to the "
                        "planner", accepted, self.this_host)
            flight_record("results_flushed", host=self.this_host,
                          n=accepted)
        except RpcError:
            with self._pending_lock:
                # Prepend: results queued while we were flushing stay
                # behind the ones that were already waiting
                self._pending_results[:0] = batch
                self._pending_bytes += sum(self._result_cost(m)
                                           for m in batch)

    def resync_result_interest(self) -> bool:
        """Re-register this host's interest in waited-on results: every
        outstanding wait when a restart signal set _resync_all (a
        restarted planner replays results but not its waiter map —
        without this, a worker blocked in get_message_result would hang
        to its timeout even though the result lands normally), else
        just the ids blocked waiters nudged (a suspected lost push must
        not put every other wait back on a per-tick poll). Returns
        False when an RpcError cut the round short; _resync_all then
        stays set for the next tick, and dropped nudges re-fire from
        their waiters' own intervals."""
        with self._results_lock:
            full = self._resync_all
            nudged = self._resync_nudged
            self._resync_nudged = set()
            pending = [(mid, app) for mid, app in
                       self._result_interest.items()
                       if mid in self._result_events
                       and (full or mid in nudged)]
        for msg_id, app_id in pending:
            try:
                resp = self.sync_send(int(PlannerCalls.GET_MESSAGE_RESULT), {
                    "app_id": app_id, "msg_id": msg_id,
                    "host": self.this_host,
                }, idempotent=True)
            except RpcError:
                return False  # next keep-alive tick retries
            if resp.header.get("found"):
                result = messages_from_wire([resp.header["msg"]],
                                            resp.payload)[0]
                self.set_message_result_locally(result)
        if full:
            with self._results_lock:
                self._resync_all = False
        return True

    def set_message_result_locally(self, msg: Message) -> None:
        """Resolve a local waiter (called by our FunctionCallServer when the
        planner pushes a result; reference setMessageResultLocally)."""
        _LC.stamp(msg, PHASE_WAITER_WAKE)
        with self._results_lock:
            if msg.id not in self._local_results:
                self._local_results_order.append(msg.id)
            self._local_results[msg.id] = msg
            while len(self._local_results_order) > self.MAX_CACHED_RESULTS:
                oldest = self._local_results_order.pop(0)
                self._local_results.pop(oldest, None)
            self._result_interest.pop(msg.id, None)
            self._result_waiters.pop(msg.id, None)
            self._resync_nudged.discard(msg.id)
            ev = self._result_events.pop(msg.id, None)
            if ev is not None:
                ev.set()

    def _drop_result_waiter_locked(self, msg_id: int) -> None:
        """One waiter gave up (RPC failure or timeout). The Event in
        _result_events is SHARED by every thread waiting on the same
        msg_id, so the registration only unwinds when the LAST waiter
        leaves — popping it eagerly would orphan a healthy concurrent
        wait (its result would land in _local_results with nobody
        calling ev.set(), and resync would skip the id too)."""
        n = self._result_waiters.get(msg_id, 1) - 1
        if n <= 0:
            self._result_waiters.pop(msg_id, None)
            self._result_events.pop(msg_id, None)
            self._result_interest.pop(msg_id, None)
            self._resync_nudged.discard(msg_id)
        else:
            self._result_waiters[msg_id] = n

    def get_message_result(self, app_id: int, msg_id: int,
                           timeout: float | None = None) -> Message:
        """Blocking result fetch. Registers interest with the planner; the
        result arrives in the sync response (already done) or is pushed to
        this host's FunctionCallServer."""
        conf = get_system_config()
        timeout = timeout if timeout is not None else conf.global_message_timeout

        with self._results_lock:
            cached = self._local_results.get(msg_id)
            if cached is not None:
                return cached
            ev = self._result_events.setdefault(msg_id, threading.Event())
            self._result_interest[msg_id] = app_id
            self._result_waiters[msg_id] = \
                self._result_waiters.get(msg_id, 0) + 1

        try:
            resp = self.sync_send(int(PlannerCalls.GET_MESSAGE_RESULT), {
                "app_id": app_id, "msg_id": msg_id, "host": self.this_host,
            }, idempotent=True)
            if resp.header.get("found"):
                result = messages_from_wire([resp.header["msg"]],
                                            resp.payload)[0]
                self.set_message_result_locally(result)
                return result
        except Exception:
            # RpcError or a decode failure alike: the caller sees it and
            # owns the retry — a leaked entry here would otherwise sit
            # in _result_interest and be re-polled on every resync
            # round forever.
            with self._results_lock:
                self._drop_result_waiter_locked(msg_id)
            raise

        # Wait for the push, nudging the keep-alive thread each interval
        # as a safety net: the planner pops the waiter set BEFORE its
        # fire-and-forget push, so a push lost on a dead pooled
        # connection (first write "succeeds" into the kernel buffer) is
        # never re-sent — and a healthy planner fires none of the
        # restart signals that trigger the resync. The waiter itself
        # never issues the RPC (a hung planner would hold the sync lock
        # past this caller's deadline and starve the keep-alive tick);
        # it only nudges its OWN msg_id, and the keep-alive thread's
        # next resync round re-polls the nudged ids with its own error
        # handling. Deadline stays exact; a prompt push costs nothing;
        # the nudge interval doubles each round (lost pushes from a
        # healthy planner are rare — a long-running app's waits must
        # not re-create the per-tick poll this gating removed).
        # Clients with no keep-alive thread get no lost-push recovery,
        # as before.
        poll = max(0.1, float(conf.planner_host_timeout) / 2)
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                with self._results_lock:
                    # The result may have landed between the wait
                    # expiring and this lock: honour it over the timeout
                    late = self._local_results.get(msg_id)
                    if late is not None:
                        return late
                    self._drop_result_waiter_locked(msg_id)
                raise TimeoutError(
                    f"Timed out waiting for result of msg {msg_id} "
                    f"(app {app_id})")
            if ev.wait(min(remaining, poll)):
                with self._results_lock:
                    return self._local_results[msg_id]
            with self._results_lock:
                self._resync_nudged.add(msg_id)
            poll = min(poll * 2, 240.0)

    def get_batch_results(self, app_id: int) -> BatchExecuteRequestStatus:
        resp = self.sync_send(int(PlannerCalls.GET_BATCH_RESULTS),
                              {"app_id": app_id}, idempotent=True)
        msgs = messages_from_wire(resp.header.get("messages", []), resp.payload)
        return BatchExecuteRequestStatus(
            app_id=resp.header["app_id"],
            finished=resp.header["finished"],
            message_results=msgs,
            expected_num_messages=resp.header["expected_num_messages"],
        )

    def get_scheduling_decision(self, app_id: int) -> Optional[SchedulingDecision]:
        resp = self.sync_send(int(PlannerCalls.GET_SCHEDULING_DECISION),
                              {"app_id": app_id}, idempotent=True)
        if not resp.header.get("found"):
            return None
        return SchedulingDecision.from_dict(resp.header["decision"])

    def relay_group_abort(self, group_id: int, reason: str,
                          hosts: list[str]) -> None:
        """Ask the planner to deliver a group abort to hosts this
        process could not reach directly (network partition): the
        planner↔host links are independent of the partitioned
        worker-pair link. Fire-and-forget — the relay is best-effort on
        top of keep-alive expiry."""
        if is_mock_mode():
            return
        self.async_send(int(PlannerCalls.RELAY_GROUP_ABORT), {
            "group_id": group_id, "reason": reason, "hosts": list(hosts)})

    def get_num_migrations(self) -> int:
        resp = self.sync_send(int(PlannerCalls.GET_NUM_MIGRATIONS),
                              idempotent=True)
        return int(resp.header["num_migrations"])

    def check_migration(self, app_id: int) -> Optional[SchedulingDecision]:
        """Ask the planner for a migration opportunity (reference
        checkForMigrationOpportunities → DIST_CHANGE)."""
        resp = self.sync_send(int(PlannerCalls.CHECK_MIGRATION),
                              {"app_id": app_id})
        if not resp.header.get("found"):
            return None
        return SchedulingDecision.from_dict(resp.header["decision"])

    def join_device_plane(self, n_processes: int):
        """One join/poll step for the multi-process device plane
        (parallel/distributed.py): None until the roster is full, then
        this host's DevicePlaneSpec. Idempotent — the planner remembers
        this host's slot across polls."""
        from faabric_tpu.parallel.distributed import DevicePlaneSpec

        resp = self.sync_send(int(PlannerCalls.JOIN_DEVICE_PLANE), {
            "host": self.this_host, "n_processes": n_processes,
        }, idempotent=True)
        if not resp.header.get("found"):
            return None
        return DevicePlaneSpec.from_dict(resp.header["spec"])

    def claim_state_master(self, user: str,
                           key: str) -> tuple[str, str, int]:
        """Resolve a key's placement, claiming mastership for this host
        if unowned. Returns ``(master, backup, epoch)`` — backup is ""
        and epoch 0 when replication is off (FAABRIC_STATE_REPLICAS=0)
        or against a pre-ISSUE-19 planner."""
        resp = self.sync_send(int(PlannerCalls.CLAIM_STATE_MASTER), {
            "user": user, "key": key, "host": self.this_host,
        }, idempotent=True)
        h = resp.header
        return (h["master"], h.get("backup", ""), int(h.get("epoch", 0)))

    def drop_state_master(self, user: str, key: str) -> None:
        self.sync_send(int(PlannerCalls.DROP_STATE_MASTER),
                       {"user": user, "key": key}, idempotent=True)

    def preload_scheduling_decision(self, decision: SchedulingDecision) -> None:
        self.sync_send(int(PlannerCalls.PRELOAD_SCHEDULING_DECISION),
                       {"decision": decision.to_dict()}, idempotent=True)

    # ------------------------------------------------------------------
    def clear_local_cache(self) -> None:
        with self._results_lock:
            self._local_results.clear()
            self._local_results_order.clear()
            self._result_events.clear()
            self._result_interest.clear()
        with self._pending_lock:
            self._pending_results.clear()
            self._recent_results.clear()
            self._out_results.clear()
            self._pending_bytes = 0
            self._recent_bytes = 0

    def close(self) -> None:
        if self._keep_alive is not None:
            self._keep_alive.stop()
            self._keep_alive = None
        if self._planner_snapshot_client is not None:
            self._planner_snapshot_client.close()
            self._planner_snapshot_client = None
        super().close()
