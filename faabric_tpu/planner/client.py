"""Per-host planner client.

Reference analog: src/planner/PlannerClient.cpp (429 lines) — including the
blocking getMessageResult with a local promise cache (the planner registers
the host's interest and pushes the result to the host's FunctionCallServer,
which resolves the promise; :202-270), callFunctions (:283-370) and the
KeepAliveThread re-registering the host every half-timeout
(PlannerClient.h:21-33).

Mock mode records batch calls / results instead of sending.
"""

from __future__ import annotations

import threading
from typing import Optional

from faabric_tpu.batch_scheduler.decision import SchedulingDecision
from faabric_tpu.faults import SUPPRESS, fault_point, faults_enabled
from faabric_tpu.planner.server import PlannerCalls
from faabric_tpu.proto import (
    BatchExecuteRequest,
    BatchExecuteRequestStatus,
    BatchExecuteType,
    Message,
    ber_to_wire,
    get_main_thread_snapshot_key,
    messages_from_wire,
    messages_to_wire,
)
from faabric_tpu.transport.client import MessageEndpointClient
from faabric_tpu.transport.common import PLANNER_ASYNC_PORT, PLANNER_SYNC_PORT
from faabric_tpu.util.config import get_system_config
from faabric_tpu.util.logging import get_logger
from faabric_tpu.util.periodic import PeriodicBackgroundThread
from faabric_tpu.util.testing import is_mock_mode

logger = get_logger(__name__)

_FAULTS = faults_enabled()
_FP_KEEPALIVE = fault_point("keepalive")

# ---------------------------------------------------------------------------
# Mock recording
# ---------------------------------------------------------------------------
_mock_lock = threading.Lock()
_mock_batch_calls: list[BatchExecuteRequest] = []
_mock_results: list[Message] = []


def get_mock_batch_calls() -> list[BatchExecuteRequest]:
    with _mock_lock:
        return list(_mock_batch_calls)


def get_mock_set_results() -> list[Message]:
    with _mock_lock:
        return list(_mock_results)


def clear_mock_planner_calls() -> None:
    with _mock_lock:
        _mock_batch_calls.clear()
        _mock_results.clear()


class KeepAliveThread(PeriodicBackgroundThread):
    def __init__(self, client: "PlannerClient", slots: int, n_devices: int) -> None:
        super().__init__()
        self.client = client
        self.slots = slots
        self.n_devices = n_devices

    def do_work(self) -> None:
        if _FAULTS and _FP_KEEPALIVE.fire(
                host=self.client.this_host) is SUPPRESS:
            # Injected keep-alive loss: the planner expires this (alive)
            # host — the chaos recipe for exercising expiry recovery and
            # the rejoin path without killing a process
            return
        self.client.register_host(self.slots, self.n_devices, rejoin=True)


class PlannerClient(MessageEndpointClient):
    """One per worker runtime, carrying the worker's host identity."""

    def __init__(self, this_host: str = "",
                 planner_host: str | None = None) -> None:
        conf = get_system_config()
        super().__init__(planner_host or conf.planner_host,
                         PLANNER_ASYNC_PORT, PLANNER_SYNC_PORT)
        self.this_host = this_host
        self._keep_alive: Optional[KeepAliveThread] = None

        # Set by the WorkerRuntime; used to push main-thread snapshots to
        # the planner ahead of THREADS batches
        self.snapshot_registry = None
        self._planner_snapshot_client = None

        # Local result promises: msg_id → Event; results land either via the
        # planner's push to our FunctionCallServer or via a direct response.
        # The cache is bounded (oldest-first) — a long-lived worker must not
        # accumulate one Message per completed invocation forever.
        self._results_lock = threading.Lock()
        self._local_results: dict[int, Message] = {}
        self._local_results_order: list[int] = []
        self._result_events: dict[int, threading.Event] = {}

    MAX_CACHED_RESULTS = 10_000

    # ------------------------------------------------------------------
    def ping(self) -> bool:
        resp = self.sync_send(int(PlannerCalls.PING), idempotent=True)
        return bool(resp.header.get("pong"))

    def register_host(self, slots: int, n_devices: int = 0,
                      overwrite: bool = False, start_keep_alive: bool = False,
                      rejoin: bool = False) -> float:
        resp = self.sync_send(int(PlannerCalls.REGISTER_HOST), {
            "host": self.this_host, "slots": slots,
            "n_devices": n_devices, "overwrite": overwrite,
        }, idempotent=True)
        timeout = float(resp.header.get("host_timeout", 30.0))
        if rejoin and not overwrite and not resp.header.get("known", True):
            # Keep-alive found us UNKNOWN to the planner: we expired off
            # the registry (paused past the timeout, partitioned, or the
            # planner restarted) while staying alive. Re-register with
            # overwrite=True so the planner treats this as a boot and
            # drops any pooled connections to our assumed-dead
            # incarnation — otherwise we stay invisible forever while
            # dutifully keep-aliving a registry entry that isn't there.
            logger.warning(
                "Host %s was expired/unknown at the planner; rejoining",
                self.this_host)
            self.sync_send(int(PlannerCalls.REGISTER_HOST), {
                "host": self.this_host, "slots": slots,
                "n_devices": n_devices, "overwrite": True,
            }, idempotent=True)
        if start_keep_alive and self._keep_alive is None:
            self._keep_alive = KeepAliveThread(self, slots, n_devices)
            self._keep_alive.start(max(0.5, timeout / 2))
        return timeout

    def remove_host(self) -> None:
        if self._keep_alive is not None:
            self._keep_alive.stop()
            self._keep_alive = None
        self.sync_send(int(PlannerCalls.REMOVE_HOST), {"host": self.this_host},
                       idempotent=True)

    def get_available_hosts(self) -> list[dict]:
        resp = self.sync_send(int(PlannerCalls.GET_AVAILABLE_HOSTS),
                              idempotent=True)
        return resp.header.get("hosts", [])

    # ------------------------------------------------------------------
    def call_functions(self, req: BatchExecuteRequest) -> SchedulingDecision:
        """Invoke a batch through the planner (reference callFunctions)."""
        if is_mock_mode():
            with _mock_lock:
                _mock_batch_calls.append(req)
            return SchedulingDecision(req.app_id, req.group_id)

        # THREADS batches set the main host and push the main-thread
        # snapshot to the planner once per key (reference
        # PlannerClient.cpp:283-370 and its pushedSnapshots cache).
        if req.type == int(BatchExecuteType.THREADS) and req.messages:
            for m in req.messages:
                m.main_host = self.this_host
            if not req.snapshot_key:
                req.snapshot_key = get_main_thread_snapshot_key(req.messages[0])
            if self.snapshot_registry is not None:
                snap = self.snapshot_registry.try_get_snapshot(req.snapshot_key)
                if snap is not None:
                    # Always push the full current image: a repeated batch
                    # on the same key must not leave the planner holding a
                    # stale pre-merge copy. (The reference optimises the
                    # repeat case with pushSnapshotUpdate diffs — a future
                    # optimisation here; correctness first.)
                    from faabric_tpu.snapshot.remote import SnapshotClient

                    if self._planner_snapshot_client is None:
                        self._planner_snapshot_client = SnapshotClient(self.host)
                    self._planner_snapshot_client.push_snapshot(
                        req.snapshot_key, snap)

        header, tail = ber_to_wire(req)
        resp = self.sync_send(int(PlannerCalls.CALL_BATCH), {"ber": header}, tail)
        return SchedulingDecision.from_dict(resp.header["decision"])

    # ------------------------------------------------------------------
    def set_message_result(self, msg: Message) -> None:
        if is_mock_mode():
            with _mock_lock:
                _mock_results.append(msg)
            return
        dicts, tail = messages_to_wire([msg])
        self.async_send(int(PlannerCalls.SET_MESSAGE_RESULT),
                        {"msg": dicts[0]}, tail)

    def set_message_result_locally(self, msg: Message) -> None:
        """Resolve a local waiter (called by our FunctionCallServer when the
        planner pushes a result; reference setMessageResultLocally)."""
        with self._results_lock:
            if msg.id not in self._local_results:
                self._local_results_order.append(msg.id)
            self._local_results[msg.id] = msg
            while len(self._local_results_order) > self.MAX_CACHED_RESULTS:
                oldest = self._local_results_order.pop(0)
                self._local_results.pop(oldest, None)
            ev = self._result_events.pop(msg.id, None)
            if ev is not None:
                ev.set()

    def get_message_result(self, app_id: int, msg_id: int,
                           timeout: float | None = None) -> Message:
        """Blocking result fetch. Registers interest with the planner; the
        result arrives in the sync response (already done) or is pushed to
        this host's FunctionCallServer."""
        conf = get_system_config()
        timeout = timeout if timeout is not None else conf.global_message_timeout

        with self._results_lock:
            cached = self._local_results.get(msg_id)
            if cached is not None:
                return cached
            ev = self._result_events.setdefault(msg_id, threading.Event())

        resp = self.sync_send(int(PlannerCalls.GET_MESSAGE_RESULT), {
            "app_id": app_id, "msg_id": msg_id, "host": self.this_host,
        }, idempotent=True)
        if resp.header.get("found"):
            result = messages_from_wire([resp.header["msg"]], resp.payload)[0]
            self.set_message_result_locally(result)
            return result

        if not ev.wait(timeout):
            with self._results_lock:
                self._result_events.pop(msg_id, None)
            raise TimeoutError(
                f"Timed out waiting for result of msg {msg_id} (app {app_id})")
        with self._results_lock:
            return self._local_results[msg_id]

    def get_batch_results(self, app_id: int) -> BatchExecuteRequestStatus:
        resp = self.sync_send(int(PlannerCalls.GET_BATCH_RESULTS),
                              {"app_id": app_id}, idempotent=True)
        msgs = messages_from_wire(resp.header.get("messages", []), resp.payload)
        return BatchExecuteRequestStatus(
            app_id=resp.header["app_id"],
            finished=resp.header["finished"],
            message_results=msgs,
            expected_num_messages=resp.header["expected_num_messages"],
        )

    def get_scheduling_decision(self, app_id: int) -> Optional[SchedulingDecision]:
        resp = self.sync_send(int(PlannerCalls.GET_SCHEDULING_DECISION),
                              {"app_id": app_id}, idempotent=True)
        if not resp.header.get("found"):
            return None
        return SchedulingDecision.from_dict(resp.header["decision"])

    def get_num_migrations(self) -> int:
        resp = self.sync_send(int(PlannerCalls.GET_NUM_MIGRATIONS),
                              idempotent=True)
        return int(resp.header["num_migrations"])

    def check_migration(self, app_id: int) -> Optional[SchedulingDecision]:
        """Ask the planner for a migration opportunity (reference
        checkForMigrationOpportunities → DIST_CHANGE)."""
        resp = self.sync_send(int(PlannerCalls.CHECK_MIGRATION),
                              {"app_id": app_id})
        if not resp.header.get("found"):
            return None
        return SchedulingDecision.from_dict(resp.header["decision"])

    def join_device_plane(self, n_processes: int):
        """One join/poll step for the multi-process device plane
        (parallel/distributed.py): None until the roster is full, then
        this host's DevicePlaneSpec. Idempotent — the planner remembers
        this host's slot across polls."""
        from faabric_tpu.parallel.distributed import DevicePlaneSpec

        resp = self.sync_send(int(PlannerCalls.JOIN_DEVICE_PLANE), {
            "host": self.this_host, "n_processes": n_processes,
        }, idempotent=True)
        if not resp.header.get("found"):
            return None
        return DevicePlaneSpec.from_dict(resp.header["spec"])

    def claim_state_master(self, user: str, key: str) -> str:
        resp = self.sync_send(int(PlannerCalls.CLAIM_STATE_MASTER), {
            "user": user, "key": key, "host": self.this_host,
        }, idempotent=True)
        return resp.header["master"]

    def drop_state_master(self, user: str, key: str) -> None:
        self.sync_send(int(PlannerCalls.DROP_STATE_MASTER),
                       {"user": user, "key": key}, idempotent=True)

    def preload_scheduling_decision(self, decision: SchedulingDecision) -> None:
        self.sync_send(int(PlannerCalls.PRELOAD_SCHEDULING_DECISION),
                       {"decision": decision.to_dict()}, idempotent=True)

    # ------------------------------------------------------------------
    def clear_local_cache(self) -> None:
        with self._results_lock:
            self._local_results.clear()
            self._local_results_order.clear()
            self._result_events.clear()

    def close(self) -> None:
        if self._keep_alive is not None:
            self._keep_alive.stop()
            self._keep_alive = None
        if self._planner_snapshot_client is not None:
            self._planner_snapshot_client.close()
            self._planner_snapshot_client = None
        super().close()
