"""Pluggable executor engine (reference src/executor)."""

from faabric_tpu.executor.context import ExecutorContext
from faabric_tpu.executor.executor import (
    Executor,
    ExecutorTask,
    FunctionFrozenException,
    FunctionMigratedException,
)
from faabric_tpu.executor.factory import (
    ExecutorFactory,
    get_executor_factory,
    set_executor_factory,
)

from faabric_tpu.executor.jax_executor import (  # noqa: E402
    GuestContext,
    JaxExecutor,
    JaxExecutorFactory,
    clear_registered_functions,
    register_function,
    unregister_function,
)

__all__ = [
    "Executor",
    "ExecutorContext",
    "ExecutorFactory",
    "ExecutorTask",
    "FunctionFrozenException",
    "FunctionMigratedException",
    "GuestContext",
    "JaxExecutor",
    "JaxExecutorFactory",
    "clear_registered_functions",
    "get_executor_factory",
    "register_function",
    "set_executor_factory",
    "unregister_function",
]
