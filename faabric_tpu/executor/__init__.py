"""Pluggable executor engine (reference src/executor)."""

from faabric_tpu.executor.context import ExecutorContext
from faabric_tpu.executor.executor import (
    Executor,
    ExecutorTask,
    FunctionFrozenException,
    FunctionMigratedException,
)
from faabric_tpu.executor.factory import (
    ExecutorFactory,
    get_executor_factory,
    set_executor_factory,
)

__all__ = [
    "Executor",
    "ExecutorContext",
    "ExecutorFactory",
    "ExecutorTask",
    "FunctionFrozenException",
    "FunctionMigratedException",
    "get_executor_factory",
    "set_executor_factory",
]
