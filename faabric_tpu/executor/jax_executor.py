"""First-class executor for JAX guest functions.

Reference analog: the (user, function)-keyed guest-callback registry the
reference uses for distributed tests (tests/dist/DistTestExecutor.cpp:16-58)
and that Faasm implements with WASM modules — promoted here to the
framework's native ExecutorFactory: TPU workloads register Python/JAX
callables, get gang-scheduled by the planner, and run with their
planner-assigned chip and MPI/PTP context in hand.

Usage::

    @register_function("demo", "train_step")
    def train_step(ctx):
        world = ctx.mpi_world()           # gang's MPI world (create/join)
        dev = ctx.device                  # the chip the planner pinned
        ...
        return b"result bytes"            # → msg.output_data

    runtime = WorkerRuntime(..., factory=JaxExecutorFactory())

Return conventions: ``bytes`` → output_data + SUCCESS; ``int`` → return
value; ``None`` → SUCCESS.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

import numpy as np

from faabric_tpu.executor.executor import Executor
from faabric_tpu.executor.factory import ExecutorFactory
from faabric_tpu.proto import ReturnValue
from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

_registry: dict[tuple[str, str], Callable] = {}
_registry_lock = threading.Lock()


def register_function(user: str, name: str, fn: Optional[Callable] = None):
    """Register ``fn`` as guest function (user, name); usable as a
    decorator."""
    def _do(f: Callable) -> Callable:
        with _registry_lock:
            _registry[(user, name)] = f
        return f

    if fn is not None:
        return _do(fn)
    return _do


def unregister_function(user: str, name: str) -> None:
    with _registry_lock:
        _registry.pop((user, name), None)


def clear_registered_functions() -> None:
    with _registry_lock:
        _registry.clear()


class GuestContext:
    """What a guest function sees: its message/request, the broker, the
    chip the planner pinned this rank to, and MPI world helpers."""

    def __init__(self, executor: "JaxExecutor", msg, req) -> None:
        self.executor = executor
        self.message = msg
        self.request = req

    # -- placement ------------------------------------------------------
    @property
    def device_id(self) -> int:
        """Planner-assigned chip id (-1 when the gang carries none)."""
        broker = self.broker
        if broker is None or not self.message.group_id:
            return -1
        try:
            broker.wait_for_mappings(self.message.group_id, timeout=5.0)
            return broker.get_device_for_idx(self.message.group_id,
                                             self.message.group_idx)
        except Exception:  # noqa: BLE001 — no mappings = no pinning
            return -1

    @property
    def device(self):
        """The local jax device for this rank (falls back to device 0)."""
        import jax

        from faabric_tpu.parallel.collectives import local_devices_for_ids

        did = self.device_id
        if did < 0:
            return jax.local_devices()[0]
        return local_devices_for_ids([did])[0]

    # -- messaging ------------------------------------------------------
    @property
    def broker(self):
        sched = self.executor.scheduler
        return getattr(sched, "ptp_broker", None) if sched else None

    def mpi_world(self):
        """Create (rank 0 of an un-created world) or join this gang's MPI
        world — the reference's MPI_Init flow."""
        from faabric_tpu.mpi import get_mpi_context

        ctx = get_mpi_context()
        msg = self.message
        if msg.mpi_rank == 0 and not msg.is_mpi:
            msg.is_mpi = True
            if not msg.mpi_world_id:
                msg.mpi_world_id = msg.app_id
            if not msg.mpi_world_size:
                msg.mpi_world_size = self.request.n_messages()
            world = ctx.create_world(msg)
        else:
            world = ctx.join_world(msg)
        world.refresh_rank_hosts()
        return world

    def state(self):
        """The host's State instance (KV get/set across the cluster)."""
        sched = self.executor.scheduler
        return getattr(sched, "state", None) if sched else None


class JaxExecutor(Executor):
    """Runs registered guest callables; memory is a plain numpy image so
    snapshot/dirty tracking work unchanged."""

    DEFAULT_MEM = 64 * 1024

    def __init__(self, msg) -> None:
        super().__init__(msg)
        self.memory = np.zeros(self.DEFAULT_MEM, dtype=np.uint8)

    def get_memory_view(self):
        return self.memory

    def set_memory_size(self, size: int) -> None:
        if size > self.memory.size:
            self.memory = np.concatenate(
                [self.memory, np.zeros(size - self.memory.size, np.uint8)])

    def execute_task(self, thread_pool_idx: int, msg_idx: int, req) -> int:
        msg = req.messages[msg_idx]
        with _registry_lock:
            fn = _registry.get((msg.user, msg.function))
        if fn is None:
            msg.output_data = (
                f"no registered function {msg.user}/{msg.function}".encode())
            return int(ReturnValue.FAILED)
        try:
            result = fn(GuestContext(self, msg, req))
        except Exception as e:  # noqa: BLE001 — guest failure, not ours
            logger.exception("Guest %s/%s failed", msg.user, msg.function)
            msg.output_data = repr(e).encode()[:512]
            return int(ReturnValue.FAILED)
        if isinstance(result, bytes):
            msg.output_data = result
            return int(ReturnValue.SUCCESS)
        if isinstance(result, int):
            return result
        return int(ReturnValue.SUCCESS)


class JaxExecutorFactory(ExecutorFactory):
    def create_executor(self, msg) -> JaxExecutor:
        return JaxExecutor(msg)
