"""Thread-local execution context (reference:
include/faabric/executor/ExecutorContext.h:168-207).

Guest code running inside an executor thread can look up which executor,
batch request and message index it belongs to. On TPU this is also where a
task finds its assigned device (the chip the planner pinned its rank to).
"""

from __future__ import annotations

import threading
from typing import TYPE_CHECKING, Optional

from faabric_tpu.proto import BatchExecuteRequest, Message

if TYPE_CHECKING:  # pragma: no cover
    from faabric_tpu.executor.executor import Executor

_tls = threading.local()


class ExecutorContext:
    def __init__(self, executor: "Executor", req: BatchExecuteRequest,
                 msg_idx: int) -> None:
        self.executor = executor
        self.req = req
        self.msg_idx = msg_idx

    @property
    def msg(self) -> Message:
        return self.req.messages[self.msg_idx]

    # ------------------------------------------------------------------
    @staticmethod
    def set(executor: "Executor", req: BatchExecuteRequest, msg_idx: int) -> None:
        _tls.context = ExecutorContext(executor, req, msg_idx)

    @staticmethod
    def unset() -> None:
        _tls.context = None

    @staticmethod
    def get() -> "ExecutorContext":
        ctx = getattr(_tls, "context", None)
        if ctx is None:
            raise RuntimeError("No executor context set on this thread")
        return ctx

    @staticmethod
    def is_set() -> bool:
        return getattr(_tls, "context", None) is not None
