"""Pluggable executor factory (reference:
include/faabric/executor/ExecutorFactory.h:215-227).

The runtime embedding the framework (the Faasm analog — here, e.g. a JAX
program runner) subclasses ``ExecutorFactory`` to produce its ``Executor``
implementation; the host scheduler creates executors through the globally
registered factory.
"""

from __future__ import annotations

import threading
from typing import TYPE_CHECKING, Optional

from faabric_tpu.proto import Message

if TYPE_CHECKING:  # pragma: no cover
    from faabric_tpu.executor.executor import Executor


class ExecutorFactory:
    def create_executor(self, msg: Message) -> "Executor":
        raise NotImplementedError

    def flush_host(self) -> None:
        """Hook run when the host is flushed (reference flushHost)."""


_factory: Optional[ExecutorFactory] = None
_factory_lock = threading.Lock()


def set_executor_factory(factory: Optional[ExecutorFactory]) -> None:
    global _factory
    with _factory_lock:
        _factory = factory


def get_executor_factory() -> ExecutorFactory:
    with _factory_lock:
        if _factory is None:
            raise RuntimeError("No executor factory registered")
        return _factory
