"""Executor: the per-host execution engine.

Reference analog: src/executor/Executor.cpp:111-215 (executeTasks),
:307-581 (threadPoolThread), include/faabric/executor/Executor.h:21-118.

An executor is bound to one function (user/function) and runs one batch at a
time (claim/release). It owns a pool of worker threads with per-thread task
queues; ``execute_task`` is the virtual the embedding runtime implements —
on TPU typically a jitted JAX callable running on the chip the planner
pinned this rank to (``ExecutorContext.get().device_id``).

Snapshot restore / dirty tracking hooks (``restore``, ``get_memory_view``,
``set_memory_size``) mirror the reference's THREADS path; the snapshot layer
wires into them.
"""

from __future__ import annotations

import threading
import time
from typing import TYPE_CHECKING, Optional

from faabric_tpu.executor.context import ExecutorContext
from faabric_tpu.proto import (
    BatchExecuteRequest,
    BatchExecuteType,
    Message,
    ReturnValue,
    get_main_thread_snapshot_key,
)
from faabric_tpu.util.config import get_system_config
from faabric_tpu.util.logging import get_logger
from faabric_tpu.util.queues import Queue

if TYPE_CHECKING:  # pragma: no cover
    from faabric_tpu.scheduler.scheduler import Scheduler

logger = get_logger(__name__)

POOL_SHUTDOWN = -1


class FunctionMigratedException(Exception):
    """Thrown by guest code when it detects it must migrate
    (reference include/faabric/executor/Executor.h)."""


class FunctionFrozenException(Exception):
    """Thrown by guest code when its app is spot-frozen."""


class ExecutorTask:
    def __init__(self, msg_idx: int, req: BatchExecuteRequest) -> None:
        self.msg_idx = msg_idx
        self.req = req


class Executor:
    """Base executor; subclasses implement ``execute_task`` and the memory
    hooks."""

    def __init__(self, msg: Message) -> None:
        conf = get_system_config()
        self.bound_msg = msg
        self.id = f"{msg.user}/{msg.function}-{msg.id}"

        self.pool_size = conf.get_usable_cores()
        self._task_queues: dict[int, Queue[ExecutorTask]] = {}
        self._pool_threads: dict[int, threading.Thread] = {}

        self._claimed = False
        self._claim_lock = threading.Lock()

        self.last_exec: float = time.monotonic()

        # Batch bookkeeping: tasks outstanding in the current batch
        self._batch_lock = threading.Lock()
        self._tasks_outstanding = 0

        self._chained_lock = threading.Lock()
        self._chained_messages: dict[int, Message] = {}

        self._shutdown = False

        # Set by the scheduler right after the factory creates the executor;
        # carries host identity and the planner client used to report
        # results.
        self.scheduler: Optional["Scheduler"] = None

    # ------------------------------------------------------------------
    # Virtual hooks (reference Executor.h:60-104)
    # ------------------------------------------------------------------
    def execute_task(self, thread_pool_idx: int, msg_idx: int,
                     req: BatchExecuteRequest) -> int:
        raise NotImplementedError

    def reset(self, msg: Message) -> None:
        """Return the executor to a clean state between batches."""

    def restore(self, snapshot_key: str) -> None:
        """Map a snapshot onto this executor's memory (THREADS batches)."""

    def get_memory_view(self) -> Optional[memoryview]:
        return None

    def set_memory_size(self, size: int) -> None:
        pass

    def get_max_memory_size(self) -> int:
        return 0

    # ------------------------------------------------------------------
    # Claiming (reference Executor::tryClaim/releaseClaim)
    # ------------------------------------------------------------------
    def try_claim(self) -> bool:
        with self._claim_lock:
            if self._claimed:
                return False
            self._claimed = True
            return True

    def release_claim(self) -> None:
        with self._claim_lock:
            self._claimed = False

    def is_claimed(self) -> bool:
        with self._claim_lock:
            return self._claimed

    # ------------------------------------------------------------------
    # Batch execution
    # ------------------------------------------------------------------
    def execute_tasks(self, msg_idxs: list[int], req: BatchExecuteRequest) -> None:
        logger.debug("%s executing %d/%d tasks of app %d", self.id,
                     len(msg_idxs), req.n_messages(), req.app_id)
        self.last_exec = time.monotonic()

        is_threads = req.type == int(BatchExecuteType.THREADS)

        # Multi-host THREADS batches restore from the main thread's snapshot
        # before any task runs (reference Executor.cpp:137-160). The
        # snapshot layer provides restore(); single-host batches skip this.
        if is_threads and not req.single_host and req.snapshot_key:
            self.restore(req.snapshot_key)

        with self._batch_lock:
            self._tasks_outstanding += len(msg_idxs)

        for msg_idx in msg_idxs:
            # Tasks spread over the pool by message index; THREADS batches
            # of up to pool_size threads therefore get one thread each.
            self._enqueue(msg_idx % self.pool_size, ExecutorTask(msg_idx, req))

    def _enqueue(self, pool_idx: int, task: ExecutorTask) -> None:
        if pool_idx not in self._task_queues:
            self._task_queues[pool_idx] = Queue()
            t = threading.Thread(
                target=self._pool_thread_loop, args=(pool_idx,),
                name=f"{self.id}-pool-{pool_idx}", daemon=True,
            )
            self._pool_threads[pool_idx] = t
            t.start()
        self._task_queues[pool_idx].enqueue(task)

    def _pool_thread_loop(self, pool_idx: int) -> None:
        q = self._task_queues[pool_idx]
        while not self._shutdown:
            task = q.dequeue()
            if task is POOL_SHUTDOWN:
                return
            self._run_task(pool_idx, task)

    def _run_task(self, pool_idx: int, task: ExecutorTask) -> None:
        req = task.req
        msg = req.messages[task.msg_idx]
        is_threads = req.type == int(BatchExecuteType.THREADS)
        msg.executed_host = self.scheduler.host if self.scheduler else ""

        ExecutorContext.set(self, req, task.msg_idx)
        try:
            ret = self.execute_task(pool_idx, task.msg_idx, req)
        except FunctionMigratedException:
            logger.debug("%s task %d migrated", self.id, msg.id)
            ret = int(ReturnValue.MIGRATED)
        except FunctionFrozenException:
            logger.debug("%s task %d frozen", self.id, msg.id)
            ret = int(ReturnValue.FROZEN)
        except Exception as e:  # noqa: BLE001 — guest errors become results
            logger.exception("%s task %d failed", self.id, msg.id)
            ret = int(ReturnValue.FAILED)
            msg.output_data = str(e).encode()
        finally:
            ExecutorContext.unset()

        msg.return_value = ret
        msg.finish_timestamp = time.time()
        self.last_exec = time.monotonic()

        with self._batch_lock:
            self._tasks_outstanding -= 1
            last_in_batch = self._tasks_outstanding == 0

        # Report the result. THREADS results go through the thread-result
        # path (snapshot diffs ride along once the snapshot layer is in);
        # everything else reports to the planner.
        if self.scheduler is not None:
            if is_threads:
                self.scheduler.set_thread_result(msg, ret)
            else:
                self.scheduler.report_message_result(msg)

        # Last task of the batch returns the executor to the pool
        # (reference Executor.cpp:520-570).
        if last_in_batch:
            if not is_threads:
                self.reset(self.bound_msg)
            self.release_claim()
            if self.scheduler is not None:
                self.scheduler.notify_executor_idle(self)

    # ------------------------------------------------------------------
    # Chained messages (reference Executor::getChainedMessage)
    # ------------------------------------------------------------------
    def add_chained_message(self, msg: Message) -> None:
        with self._chained_lock:
            self._chained_messages[msg.id] = msg

    def get_chained_message(self, msg_id: int) -> Message:
        with self._chained_lock:
            return self._chained_messages[msg_id]

    def get_chained_message_ids(self) -> list[int]:
        with self._chained_lock:
            return list(self._chained_messages)

    # ------------------------------------------------------------------
    def shutdown(self) -> None:
        self._shutdown = True
        for idx, q in self._task_queues.items():
            q.enqueue(POOL_SHUTDOWN)
        for t in self._pool_threads.values():
            t.join(timeout=2.0)
        self._pool_threads.clear()
        self._task_queues.clear()

    def uptime_idle(self) -> float:
        return time.monotonic() - self.last_exec
