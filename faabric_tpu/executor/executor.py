"""Executor: the per-host execution engine.

Reference analog: src/executor/Executor.cpp:111-215 (executeTasks),
:307-581 (threadPoolThread), include/faabric/executor/Executor.h:21-118.

An executor is bound to one function (user/function) and runs one batch at a
time (claim/release). It owns a pool of worker threads with per-thread task
queues; ``execute_task`` is the virtual the embedding runtime implements —
on TPU typically a jitted JAX callable running on the chip the planner
pinned this rank to (``ExecutorContext.get().device_id``).

Snapshot restore / dirty tracking hooks (``restore``, ``get_memory_view``,
``set_memory_size``) mirror the reference's THREADS path; the snapshot layer
wires into them.
"""

from __future__ import annotations

import threading
import time
from typing import TYPE_CHECKING, Optional

from faabric_tpu.executor.context import ExecutorContext
from faabric_tpu.faults import fault_point, faults_enabled
from faabric_tpu.proto import (
    BatchExecuteRequest,
    BatchExecuteType,
    Message,
    ReturnValue,
    get_main_thread_snapshot_key,
)
from faabric_tpu.telemetry import (
    NULL_SPAN,
    get_lifecycle,
    get_metrics,
    span,
    tracing_enabled,
)
from faabric_tpu.telemetry.lifecycle import (
    PHASE_EXEC_QUEUE_EXIT,
    PHASE_RUN_END,
    PHASE_RUN_START,
)
from faabric_tpu.util.config import get_system_config
from faabric_tpu.util.logging import get_logger
from faabric_tpu.util.queues import Queue

if TYPE_CHECKING:  # pragma: no cover
    from faabric_tpu.scheduler.scheduler import Scheduler

logger = get_logger(__name__)

POOL_SHUTDOWN = -1

_FAULTS = faults_enabled()
_FP_RUN = fault_point("executor.run")

_LC = get_lifecycle()

_metrics = get_metrics()
_QUEUE_WAIT_SECONDS = _metrics.histogram(
    "faabric_executor_queue_wait_seconds",
    "Task time spent queued before a pool thread picked it up")
_RUN_SECONDS = _metrics.histogram(
    "faabric_executor_run_seconds",
    "Guest execute_task run time")
_TASKS_TOTAL = _metrics.counter(
    "faabric_executor_tasks_total", "Tasks executed")


class FunctionMigratedException(Exception):
    """Thrown by guest code when it detects it must migrate
    (reference include/faabric/executor/Executor.h)."""


class FunctionFrozenException(Exception):
    """Thrown by guest code when its app is spot-frozen."""


class ExecutorTask:
    def __init__(self, msg_idx: int, req: BatchExecuteRequest) -> None:
        self.msg_idx = msg_idx
        self.req = req
        self.enqueue_ts = time.monotonic()


def _merge_dirty_flags(acc, new):
    """OR page-flag arrays that may differ in length (memory grown
    mid-batch: unseen pages count as dirty for the thread that grew)."""
    import numpy as np

    if acc is None:
        return new
    if acc.size == new.size:
        return acc | new
    n, m = max(acc.size, new.size), min(acc.size, new.size)
    out = np.ones(n, dtype=bool)  # grown pages are dirty by definition
    out[:m] = acc[:m] | new[:m]
    return out


class Executor:
    """Base executor; subclasses implement ``execute_task`` and the memory
    hooks."""

    def __init__(self, msg: Message) -> None:
        conf = get_system_config()
        self.bound_msg = msg
        self.id = f"{msg.user}/{msg.function}-{msg.id}"

        self.pool_size = conf.get_usable_cores()
        self._task_queues: dict[int, Queue[ExecutorTask]] = {}
        self._pool_threads: dict[int, threading.Thread] = {}

        self._claimed = False
        self._claim_lock = threading.Lock()

        self.last_exec: float = time.monotonic()

        # Batch bookkeeping: tasks outstanding in the current batch
        self._batch_lock = threading.Lock()
        self._tasks_outstanding = 0

        self._chained_lock = threading.Lock()
        self._chained_messages: dict[int, Message] = {}

        self._shutdown = False

        # Set by the scheduler right after the factory creates the executor;
        # carries host identity and the planner client used to report
        # results.
        self.scheduler: Optional["Scheduler"] = None

        # THREADS batch snapshot state (set per batch in execute_tasks)
        self._batch_snapshot_key = ""
        self._batch_tracker = None
        self._batch_dirty = None  # accumulated dirty page flags (OR)
        self._batch_hints = None  # (offset, length) write extents or None

    def _region_hints_for(self, snapshot_key: str):
        """Merge regions as write-extent hints, when DIRTY_REGION_HINTS
        promises guest writes stay inside declared regions."""
        from faabric_tpu.util.config import get_system_config

        if not get_system_config().dirty_region_hints:
            return None
        registry = getattr(self.scheduler, "snapshot_registry", None)
        if registry is None:
            return None
        snap = registry.try_get_snapshot(snapshot_key)
        if snap is None:
            return None
        regions = snap.get_merge_regions()
        if not regions:
            return None
        # Hints only help when the declared write set is a small part of
        # the image: after a previous batch's fill_gaps_with_bytewise_
        # regions() the regions span everything, and whole-image "hints"
        # bracket SLOWER than plain tracking (fancy-index page copies)
        covered = sum(r.length for r in regions)
        if covered * 2 >= snap.size:
            return None
        return [(r.offset, r.length) for r in regions]

    # ------------------------------------------------------------------
    # Virtual hooks (reference Executor.h:60-104)
    # ------------------------------------------------------------------
    def execute_task(self, thread_pool_idx: int, msg_idx: int,
                     req: BatchExecuteRequest) -> int:
        raise NotImplementedError

    def reset(self, msg: Message) -> None:
        """Return the executor to a clean state between batches."""

    def restore(self, snapshot_key: str) -> None:
        """Map a snapshot onto this executor's memory (THREADS batches).
        Default: fetch from the host's registry, size memory, copy in
        (reference Executor.cpp:640-654)."""
        registry = getattr(self.scheduler, "snapshot_registry", None)
        if registry is None:
            return
        snap = registry.get_snapshot(snapshot_key)
        self.set_memory_size(snap.size)
        mem = self.get_memory_view()
        if mem is not None:
            snap.map_to_memory(mem)

    def get_memory_view(self) -> Optional[memoryview]:
        return None

    def set_memory_size(self, size: int) -> None:
        pass

    def get_max_memory_size(self) -> int:
        return 0

    # ------------------------------------------------------------------
    # Claiming (reference Executor::tryClaim/releaseClaim)
    # ------------------------------------------------------------------
    def try_claim(self) -> bool:
        with self._claim_lock:
            if self._claimed:
                return False
            self._claimed = True
            return True

    def release_claim(self) -> None:
        with self._claim_lock:
            self._claimed = False

    def is_claimed(self) -> bool:
        with self._claim_lock:
            return self._claimed

    # ------------------------------------------------------------------
    # Batch execution
    # ------------------------------------------------------------------
    def execute_tasks(self, msg_idxs: list[int], req: BatchExecuteRequest) -> None:
        logger.debug("%s executing %d/%d tasks of app %d", self.id,
                     len(msg_idxs), req.n_messages(), req.app_id)
        self.last_exec = time.monotonic()

        is_threads = req.type == int(BatchExecuteType.THREADS)

        # Multi-host THREADS batches restore from the main thread's snapshot
        # before any task runs and start dirty tracking so each thread's
        # writes can merge back as diffs (reference Executor.cpp:137-160).
        self._batch_snapshot_key = ""
        self._batch_tracker = None
        self._batch_dirty = None
        if is_threads and not req.single_host and req.snapshot_key:
            self.restore(req.snapshot_key)
            mem = self.get_memory_view()
            if mem is not None:
                from faabric_tpu.util.dirty import make_dirty_tracker

                self._batch_snapshot_key = req.snapshot_key
                self._batch_tracker = make_dirty_tracker()
                # Opt-in region hints: when the batch's snapshot declares
                # merge regions AND the config promises writes stay inside
                # them, bracketing cost scales with the declared write
                # set, not the image (VERDICT r2 weak #4)
                self._batch_hints = self._region_hints_for(req.snapshot_key)
                self._batch_tracker.start_tracking(
                    mem, region_hints=self._batch_hints)

        with self._batch_lock:
            self._tasks_outstanding += len(msg_idxs)

        for msg_idx in msg_idxs:
            # Tasks spread over the pool by message index; THREADS batches
            # of up to pool_size threads therefore get one thread each.
            self._enqueue(msg_idx % self.pool_size, ExecutorTask(msg_idx, req))

    def _enqueue(self, pool_idx: int, task: ExecutorTask) -> None:
        if pool_idx not in self._task_queues:
            self._task_queues[pool_idx] = Queue()
            t = threading.Thread(
                target=self._pool_thread_loop, args=(pool_idx,),
                name=f"executor/pool@{self.id}-{pool_idx}", daemon=True,
            )
            self._pool_threads[pool_idx] = t
            t.start()
        self._task_queues[pool_idx].enqueue(task)

    def _pool_thread_loop(self, pool_idx: int) -> None:
        q = self._task_queues[pool_idx]
        while not self._shutdown:
            task = q.dequeue()
            if task is POOL_SHUTDOWN:
                return
            try:
                self._run_task(pool_idx, task)
            except Exception:  # noqa: BLE001 — a reporting failure must not
                # kill the pool thread; the task's own errors are already
                # folded into its result inside _run_task
                logger.exception("%s result handling failed for task %d",
                                 self.id, task.msg_idx)

    def _run_task(self, pool_idx: int, task: ExecutorTask) -> None:
        req = task.req
        msg = req.messages[task.msg_idx]
        is_threads = req.type == int(BatchExecuteType.THREADS)
        msg.executed_host = self.scheduler.host if self.scheduler else ""
        # Lifecycle ledger (ISSUE 14): the pool thread has the task
        _LC.stamp(msg, PHASE_EXEC_QUEUE_EXIT)
        queue_wait = time.monotonic() - task.enqueue_ts
        _QUEUE_WAIT_SECONDS.observe(queue_wait)

        # Thread-local dirty tracking brackets the task so each thread
        # reports only its own writes (reference Executor.cpp:464-476)
        tracker = self._batch_tracker
        mem = self.get_memory_view() if tracker is not None else None
        if tracker is not None and mem is not None:
            tracker.start_thread_local_tracking(
                mem, region_hints=self._batch_hints)

        ExecutorContext.set(self, req, task.msg_idx)
        _LC.stamp(msg, PHASE_RUN_START)
        run_t0 = time.monotonic()
        try:
            if _FAULTS:
                # delay rules make stragglers; raise rules fail the task
                # (the generic handler below folds it into the result)
                _FP_RUN.fire(function=f"{msg.user}/{msg.function}",
                             msg_id=msg.id)
            with span("executor", "execute_task", msg_id=msg.id,
                      function=f"{msg.user}/{msg.function}") \
                    if tracing_enabled() else NULL_SPAN:
                ret = self.execute_task(pool_idx, task.msg_idx, req)
        except FunctionMigratedException:
            logger.debug("%s task %d migrated", self.id, msg.id)
            ret = int(ReturnValue.MIGRATED)
        except FunctionFrozenException:
            logger.debug("%s task %d frozen", self.id, msg.id)
            ret = int(ReturnValue.FROZEN)
        except Exception as e:  # noqa: BLE001 — guest errors become results
            logger.exception("%s task %d failed", self.id, msg.id)
            ret = int(ReturnValue.FAILED)
            msg.output_data = str(e).encode()
            # Post-mortem: the unhandled guest exception is a flight-dump
            # trigger — the ring's recent sends/faults around it are the
            # context a stack trace alone cannot give. Guarded: recording
            # must never replace the handled guest error (the FAILED
            # result still has to reach the planner).
            try:
                from faabric_tpu.telemetry import (
                    flight_dump,
                    flight_record,
                )

                flight_record("executor_exception", msg_id=msg.id,
                              function=f"{msg.user}/{msg.function}",
                              error=str(e)[:200])
                flight_dump("executor_exception")
            except Exception:  # noqa: BLE001
                logger.exception("Flight dump on task failure failed")
        finally:
            ExecutorContext.unset()

        _LC.stamp(msg, PHASE_RUN_END)
        run_seconds = time.monotonic() - run_t0
        _RUN_SECONDS.observe(run_seconds)
        _TASKS_TOTAL.inc()
        msg.return_value = ret
        msg.finish_timestamp = time.time()
        # Per-message timing rides the result into the planner, so
        # ExecGraph.to_json() can report wall/queue/exec durations per
        # node (util/exec_graph.py)
        msg.int_exec_graph_details["queue_us"] = int(queue_wait * 1e6)
        msg.int_exec_graph_details["exec_us"] = int(run_seconds * 1e6)
        self.last_exec = time.monotonic()

        # Each thread contributes its dirty pages BEFORE the outstanding
        # count drops: the decrement elects the last thread, and that
        # thread must see every earlier thread's pages when it computes the
        # batch diff (reference Executor.cpp:684-737 mergeDirtyRegions).
        if is_threads and tracker is not None and mem is not None:
            tracker.stop_thread_local_tracking(mem)
            dirty = tracker.get_thread_local_dirty_pages(mem)
            with self._batch_lock:
                self._batch_dirty = _merge_dirty_flags(self._batch_dirty,
                                                       dirty)

        with self._batch_lock:
            self._tasks_outstanding -= 1
            last_in_batch = self._tasks_outstanding == 0

        # Report the result. THREADS results carry the batch's snapshot
        # diffs back to the main host (computed once, by the last task);
        # everything else reports straight to the planner.
        if self.scheduler is not None:
            if is_threads:
                diffs = None
                if last_in_batch and mem is not None:
                    registry = getattr(self.scheduler,
                                       "snapshot_registry", None)
                    if registry is not None and self._batch_snapshot_key:
                        snap = registry.try_get_snapshot(
                            self._batch_snapshot_key)
                        if snap is not None:
                            with self._batch_lock:
                                batch_dirty = self._batch_dirty
                            if batch_dirty is not None:
                                # Writes outside declared merge regions must
                                # not vanish (reference Executor.cpp:713)
                                snap.fill_gaps_with_bytewise_regions()
                                diffs = snap.diff_with_dirty_regions(
                                    mem, batch_dirty)
                self.scheduler.report_thread_result(
                    msg, ret, self._batch_snapshot_key, diffs)
            else:
                self.scheduler.report_message_result(msg)

        # Last task of the batch returns the executor to the pool
        # (reference Executor.cpp:520-570).
        if last_in_batch:
            if is_threads and self._batch_tracker is not None:
                # Unprotect/retire the batch-level bracket now rather than
                # at the next batch's reassignment: segv mode would
                # otherwise leave untouched pages PROT_READ and charge
                # later non-THREADS work a fault per page
                if mem is None:
                    mem = self.get_memory_view()
                if mem is not None:
                    self._batch_tracker.stop_tracking(mem)
                self._batch_tracker = None
            if not is_threads:
                self.reset(self.bound_msg)
            self.release_claim()
            if self.scheduler is not None:
                self.scheduler.notify_executor_idle(self)

    # ------------------------------------------------------------------
    # Chained messages (reference Executor::getChainedMessage)
    # ------------------------------------------------------------------
    def add_chained_message(self, msg: Message) -> None:
        with self._chained_lock:
            self._chained_messages[msg.id] = msg

    def get_chained_message(self, msg_id: int) -> Message:
        with self._chained_lock:
            return self._chained_messages[msg_id]

    def get_chained_message_ids(self) -> list[int]:
        with self._chained_lock:
            return list(self._chained_messages)

    # ------------------------------------------------------------------
    def shutdown(self) -> None:
        self._shutdown = True
        for idx, q in self._task_queues.items():
            q.enqueue(POOL_SHUTDOWN)
        for t in self._pool_threads.values():
            t.join(timeout=2.0)
        self._pool_threads.clear()
        self._task_queues.clear()

    def uptime_idle(self) -> float:
        return time.monotonic() - self.last_exec
