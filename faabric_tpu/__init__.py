"""faabric_tpu — a TPU-native distributed-runtime framework.

Provides scheduling, messaging and state for distributed accelerated
runtimes: the capabilities of faasm/faabric (reference: /root/reference,
v0.22.0) re-designed TPU-first.

  - Device compute and collectives go through JAX/XLA (pjit / shard_map over
    a ``jax.sharding.Mesh``), riding ICI; the reference's leader-tree
    collectives over raw TCP (``src/mpi/MpiWorld.cpp``) become compiled XLA
    collectives wherever the op matches.
  - The host-side runtime (planner control plane, per-host scheduler,
    executor pool, point-to-point broker, state KV, snapshots) mirrors the
    reference's process topology (``src/runner/FaabricMain.cpp``) with a
    framed-TCP transport in place of nng.

Layer map (== SURVEY.md §1; every layer is implemented — see README.md):

    endpoint/        HTTP REST API (planner controller)
    planner/         cluster-singleton control plane + state-master registry
    batch_scheduler/ pluggable scheduling policies (bin-pack/compact/spot)
    scheduler/       per-host scheduler, function-call RPC, chaining
    executor/        pluggable executor w/ thread pool, snapshot restore
    mpi/             MPI-semantics world: host PTP path + XLA device path,
                     sub-communicators, guest mpi_* API
    transport/       framed TCP endpoints, RPC servers/clients, PTP broker
                     with ordered delivery + group locks/barriers
    snapshot/        memory snapshots, typed merge regions, diffs, deltas
    state/           distributed KV (master-per-key, chunked pull/push)
    parallel/        TPU mesh substrate: axes, collectives, device p2p,
                     ring attention, pipeline parallelism
    models/          dense + MoE families over dp/tp/sp/pp/ep, sampling
                     decode, gradient accumulation, eval, checkpointing
    data/            memmap token datasets + prefetching mesh loaders
    ops/             Pallas kernels (flash attention fwd+bwd w/ lse,
                     fused RMS norm)
    runner/          worker runtime assembly + deployment CLI
    util/            config, gids, queues, latches, dirty tracking, graphs,
                     CPU pinning, crash handler, native-lib loader
    native/          C++ page-diff/XOR kernels (repo root, ctypes-bound)
"""

__version__ = "0.3.0"
