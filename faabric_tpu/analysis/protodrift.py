"""Static protocol-drift pass over the RPC call enums.

Two invariants, checked over the whole package AST:

- **handler coverage** — every member of a call enum (class name ending
  in ``Calls``/``Call``, e.g. ``PlannerCalls``, ``PointToPointCall``)
  must be referenced inside some server dispatch function
  (``do_sync_recv``/``do_async_recv``). A member nobody dispatches on is
  wire surface the server silently rejects — exactly the drift that
  turns a new client call into "Unknown sync planner call N" at runtime.
  Members prefixed ``NO_`` (the proto null values) are exempt.
- **declared members** — every ``SomeEnum.MEMBER`` attribute access in
  the package must name a declared member of that enum. Python only
  raises on these at call time, so a typo in a rarely-exercised branch
  (an error path, a chaos-only RPC) survives every green test run until
  production hits it. This covers all IntEnums, including the MPI wire
  enums (``MpiMessageType``/``MpiOp``/``MpiDataType``).

Findings use the shared ``guards.Finding`` shape so ``tools/concheck.py``
ratchets them through the same baseline.
"""

from __future__ import annotations

import ast
import os

from faabric_tpu.analysis.guards import Finding

__all__ = ["analyze_package"]

_DISPATCH_FUNCS = ("do_sync_recv", "do_async_recv")


def _is_int_enum(node: ast.ClassDef) -> bool:
    for base in node.bases:
        if isinstance(base, ast.Attribute) and base.attr in (
                "IntEnum", "Enum", "IntFlag"):
            return True
        if isinstance(base, ast.Name) and base.id in (
                "IntEnum", "Enum", "IntFlag"):
            return True
    return False


def _enum_members(node: ast.ClassDef) -> dict[str, int]:
    out: dict[str, int] = {}
    for stmt in node.body:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name) and t.id.isupper():
                    out[t.id] = stmt.lineno
    return out


class _Module:
    def __init__(self, rel: str, tree: ast.Module) -> None:
        self.rel = rel
        self.tree = tree


def _walk_package(root: str, subdirs: tuple[str, ...]) -> list[_Module]:
    mods = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                with open(full, encoding="utf-8") as f:
                    try:
                        tree = ast.parse(f.read())
                    except SyntaxError:
                        continue  # guards pass reports parse errors
                mods.append(_Module(os.path.relpath(full, root), tree))
    return mods


def _attr_refs(node: ast.AST) -> list[tuple[str, str, int]]:
    """Every ``Name.UPPER`` attribute access under ``node``."""
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name) \
                and n.attr.isupper():
            out.append((n.value.id, n.attr, n.lineno))
    return out


def analyze_package(root: str, subdirs: tuple[str, ...] = ("faabric_tpu",)
                    ) -> list[Finding]:
    mods = _walk_package(root, subdirs)

    # enum name → (members, defining module rel path, def line)
    enums: dict[str, tuple[dict[str, int], str, int]] = {}
    for mod in mods:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef) and _is_int_enum(node):
                enums[node.name] = (_enum_members(node), mod.rel,
                                    node.lineno)

    findings: list[Finding] = []

    # -- declared-member usage (all enums, all code) --------------------
    # Collected per (module, function-ish context) for qualnames; a flat
    # walk is enough since the fingerprint carries the subject.
    handled: dict[str, set[str]] = {name: set() for name in enums}
    for mod in mods:
        in_dispatch: list[tuple[str, str, int]] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in _DISPATCH_FUNCS:
                in_dispatch.extend(_attr_refs(node))
        for enum_name, member, _line in in_dispatch:
            if enum_name in enums:
                handled[enum_name].add(member)
        for enum_name, member, line in _attr_refs(mod.tree):
            info = enums.get(enum_name)
            if info is None:
                continue
            members, _, _ = info
            if member not in members and not member.startswith("_"):
                findings.append(Finding(
                    path=mod.rel, line=line, rule="undeclared-call-member",
                    qualname="<module>", subject=f"{enum_name}.{member}",
                    message=f"{enum_name}.{member} is not a declared "
                            f"member of {enum_name} (protocol drift: "
                            f"this raises AttributeError when reached)"))

    # -- handler coverage (call enums only) -----------------------------
    for enum_name, (members, rel, line) in enums.items():
        if not (enum_name.endswith("Calls") or enum_name.endswith("Call")):
            continue
        for member, mline in sorted(members.items()):
            if member.startswith("NO_"):
                continue
            if member not in handled[enum_name]:
                findings.append(Finding(
                    path=rel, line=mline, rule="unhandled-call",
                    qualname=enum_name, subject=member,
                    message=f"{enum_name}.{member} has no registered "
                            f"server handler (no do_sync_recv/"
                            f"do_async_recv references it)"))
    return findings
