"""AST guarded-by lint: verify annotated shared state is only touched
under its lock.

Annotation convention (docs/static_analysis.md):

- A class (or module) declares its guard map either as a literal class
  attribute::

      GUARDS = {"_hosts": "_lock", "_waiters": "_lock"}

  (values name a lock attribute on ``self``; module-level maps name
  module globals), or per-assignment with a trailing comment in
  ``__init__`` / at module scope::

      self._hosts = {}  # guard: self._lock

- Only annotated attributes are checked — the map IS the contract.
  Deliberately lock-free accesses (documented fast paths, benign races)
  either stay out of the map or carry a line pragma::

      # concheck: ok                      (suppress every rule here)
      # concheck: ok(blocking-under-lock) (suppress specific rules)

Rules:

- ``guard-unlocked``     — a guarded attribute is read or written while
  its lock is not held (``with`` scopes only; ``__init__`` is exempt,
  and ``*_locked`` methods are assumed to run under every class lock,
  per the repo convention).
- ``check-then-act``     — a guarded read escapes its lock into a local
  and a later, *separate* acquisition of the same lock writes the same
  attribute conditioned on (or computed from) that stale local.
- ``blocking-under-lock`` — a known-blocking call (socket ops, RPC
  ``sync_send``/``async_send``, indefinite ``.wait()``/``.join()``,
  ``time.sleep``, ``subprocess.*``) happens while any lock is held.

The lint is deliberately heuristic: findings ratchet through
``tools/concheck_baseline.txt`` (the failure_gate pattern), so a rare
false positive is baselined or pragma'd with a justification instead of
weakening the analyzer.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

__all__ = ["Finding", "analyze_source", "analyze_file", "analyze_paths"]

_GUARD_COMMENT = re.compile(r"#\s*guard:\s*([A-Za-z_][\w.]*)")
_PRAGMA = re.compile(r"#\s*concheck:\s*ok(?:\(([^)]*)\))?")

# Calls that can block the calling thread for network/scheduler time.
# Matched on the attribute name of the call (x.recv(...)); module-style
# calls (time.sleep, subprocess.run) are matched on the dotted pair.
_BLOCKING_METHODS = frozenset({
    "recv", "recv_into", "recvfrom", "send", "sendall", "sendmsg",
    "accept", "connect", "connect_ex", "sync_send", "async_send",
    "communicate",
})
_BLOCKING_DOTTED = frozenset({
    ("time", "sleep"),
    ("socket", "create_connection"),
    ("socket", "getaddrinfo"),
    ("subprocess", "run"), ("subprocess", "call"),
    ("subprocess", "check_call"), ("subprocess", "check_output"),
})
# Indefinite parks: flagged only with no timeout argument (``ev.wait()``,
# ``t.join()``) or an explicit ``None`` timeout.
_INDEFINITE_METHODS = frozenset({"wait", "join"})


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str       # repo-relative
    line: int
    rule: str
    qualname: str   # Class.method / module-level function / "<module>"
    subject: str    # attr or call text the finding is about
    message: str

    @property
    def fingerprint(self) -> str:
        # Line numbers deliberately excluded: the committed baseline must
        # survive unrelated edits above the finding (failure_gate style)
        return f"{self.path}::{self.qualname}::{self.rule}::{self.subject}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule}: {self.message} "
                f"[{self.qualname}]")


def _is_lock_name(text: str) -> bool:
    last = text.rsplit(".", 1)[-1]
    return "lock" in last.lower() or last in ("_mx", "mx")


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - malformed nodes
        return "<expr>"


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class _Pragmas:
    """Line → set of suppressed rules (empty set = all rules)."""

    def __init__(self, source: str) -> None:
        self.by_line: dict[int, frozenset[str]] = {}
        lines = source.splitlines()
        for i, line in enumerate(lines, start=1):
            m = _PRAGMA.search(line)
            if m:
                rules = frozenset(
                    r.strip() for r in (m.group(1) or "").split(",")
                    if r.strip())
                self.by_line[i] = rules
                if line.strip().startswith("#"):
                    # A comment-only pragma also covers the next code
                    # line (skipping the rest of its comment block) —
                    # the idiom for statements too long to carry a
                    # trailing comment
                    j = i
                    while j < len(lines) and (
                            not lines[j].strip()
                            or lines[j].strip().startswith("#")):
                        j += 1
                    self.by_line.setdefault(j + 1, rules)

    def suppressed(self, node: ast.AST, rule: str) -> bool:
        start = getattr(node, "lineno", 0)
        end = getattr(node, "end_lineno", start) or start
        for ln in range(start, end + 1):
            rules = self.by_line.get(ln)
            if rules is not None and (not rules or rule in rules):
                return True
        return False

    def suppressed_def(self, node: ast.AST) -> bool:
        """A bare ``# concheck: ok`` on the ``def`` line waives the
        whole function."""
        rules = self.by_line.get(getattr(node, "lineno", 0))
        return rules is not None and not rules


def _literal_guard_map(node: ast.Assign | ast.AnnAssign) -> dict[str, str]:
    """Parse ``GUARDS = {"_attr": "_lock"}`` literals."""
    value = node.value
    targets = (node.targets if isinstance(node, ast.Assign)
               else [node.target])
    if not any(isinstance(t, ast.Name) and t.id == "GUARDS"
               for t in targets):
        return {}
    if not isinstance(value, ast.Dict):
        return {}
    out: dict[str, str] = {}
    for k, v in zip(value.keys, value.values):
        if (isinstance(k, ast.Constant) and isinstance(k.value, str)
                and isinstance(v, ast.Constant)
                and isinstance(v.value, str)):
            out[k.value] = v.value
    return out


def _comment_guards(body: list[ast.stmt], source_lines: list[str],
                    self_name: str | None) -> dict[str, str]:
    """Trailing ``# guard: <lock>`` comments on assignments."""
    out: dict[str, str] = {}
    for stmt in body:
        if not isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            continue
        line = source_lines[stmt.lineno - 1] \
            if stmt.lineno - 1 < len(source_lines) else ""
        m = _GUARD_COMMENT.search(line)
        if not m:
            continue
        guard = m.group(1)
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target])
        for t in targets:
            if (self_name and isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == self_name):
                out[t.attr] = guard.removeprefix("self.")
            elif self_name is None and isinstance(t, ast.Name):
                out[t.id] = guard
    return out


class _Scope:
    """One class (or the module) carrying a guard map."""

    def __init__(self, name: str, guards: dict[str, str],
                 is_module: bool) -> None:
        self.name = name
        self.is_module = is_module
        # attr → normalized lock text ("self._lock" / "_mock_lock")
        self.guards = {
            attr: (lock if is_module or "." in lock else f"self.{lock}")
            for attr, lock in guards.items()
        }
        self.all_locks = set(self.guards.values())


class _FunctionWalker:
    """Walks one function body tracking the set of held locks."""

    def __init__(self, analyzer: "_Analyzer", scope: _Scope,
                 qualname: str, assume_held: frozenset[str]) -> None:
        self.a = analyzer
        self.scope = scope
        self.qualname = qualname
        self.held: list[str] = list(assume_held)
        self.session = 0                 # increments per lock acquisition
        self.session_of: dict[str, int] = {
            lk: 0 for lk in assume_held}  # lock text → current session id
        # guarded attr → (session, lock) of its last in-lock read
        self.reads: dict[str, tuple[int, str]] = {}
        # local name → (attr, session) for locals carrying guarded reads
        self.tainted: dict[str, tuple[str, int]] = {}
        self.cond_names: list[set[str]] = []  # enclosing If/While tests

    # -- helpers -------------------------------------------------------
    def _report(self, node: ast.AST, rule: str, subject: str,
                message: str) -> None:
        self.a.report(node, rule, self.qualname, subject, message)

    def _guard_for(self, attr_text: str, attr: str) -> str | None:
        """Lock text required for this access, or None if unguarded."""
        if self.scope.is_module:
            return self.scope.guards.get(attr)
        if attr_text.startswith("self."):
            return self.scope.guards.get(attr)
        return None

    # -- statement walk ------------------------------------------------
    def walk_body(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            self.walk_stmt(stmt)

    def walk_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Deferred execution: nested defs start with no locks held
            self.a.queue_function(stmt, self.scope,
                                  f"{self.qualname}.{stmt.name}",
                                  frozenset())
            return
        if isinstance(stmt, ast.ClassDef):
            self.a.visit_class(stmt, parent=self.qualname)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired: list[str] = []
            for item in stmt.items:
                self.scan_expr(item.context_expr)
                text = _unparse(item.context_expr)
                if (_is_lock_name(text)
                        or text in self.scope.all_locks):
                    acquired.append(text)
                if item.optional_vars is not None:
                    self.scan_expr(item.optional_vars)
            for lk in acquired:
                self.session += 1
                self.session_of[lk] = self.session
                self.held.append(lk)
            self.walk_body(stmt.body)
            for lk in reversed(acquired):
                self.held.remove(lk)
                self.session_of.pop(lk, None)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self.scan_expr(stmt.test)
            self.cond_names.append(_names_in(stmt.test))
            self.walk_body(stmt.body)
            self.walk_body(stmt.orelse)
            self.cond_names.pop()
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.scan_expr(stmt.iter)
            self.scan_expr(stmt.target)
            self.walk_body(stmt.body)
            self.walk_body(stmt.orelse)
            return
        if isinstance(stmt, ast.Try):
            self.walk_body(stmt.body)
            for h in stmt.handlers:
                self.walk_body(h.body)
            self.walk_body(stmt.orelse)
            self.walk_body(stmt.finalbody)
            return
        if hasattr(ast, "Match") and isinstance(stmt, ast.Match):
            self.scan_expr(stmt.subject)
            for case in stmt.cases:
                self.walk_body(case.body)
            return
        # Simple statement: scan its expressions, then record taint for
        # ``local = <expr reading guarded attr under lock>``
        self.scan_expr(stmt)
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            attrs = self._guarded_reads_in(stmt.value)
            if attrs and self.held:
                attr = attrs[0]
                lock = self.scope.guards[attr]  # normalized by _Scope
                if lock in self.held:
                    self.tainted[stmt.targets[0].id] = (
                        attr, self.session_of.get(lock, 0))

    def _guarded_reads_in(self, expr: ast.AST) -> list[str]:
        out = []
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                if self.scope.is_module:
                    continue
                if isinstance(node.value, ast.Name) \
                        and node.value.id == "self" \
                        and node.attr in self.scope.guards:
                    out.append(node.attr)
            elif isinstance(node, ast.Name) and self.scope.is_module \
                    and isinstance(node.ctx, ast.Load) \
                    and node.id in self.scope.guards:
                out.append(node.id)
        return out

    # -- expression scan -----------------------------------------------
    def scan_expr(self, expr: ast.AST) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute):
                self._check_attribute(node)
            elif isinstance(node, ast.Name):
                self._check_global(node)
            elif isinstance(node, ast.Call):
                self._check_call(node)

    def _attr_access(self, attr: str, node: ast.AST,
                     is_write: bool, text: str) -> None:
        lock = self.scope.guards.get(attr)  # normalized by _Scope
        if lock is None:
            return
        if lock in self.held:
            if not is_write:
                self.reads[attr] = (self.session_of.get(lock, 0), lock)
            else:
                self._check_check_then_act(attr, lock, node)
            return
        self._report(
            node, "guard-unlocked", attr,
            f"{'write to' if is_write else 'read of'} {text} outside "
            f"its guard {lock}")

    def _check_attribute(self, node: ast.Attribute) -> None:
        if self.scope.is_module:
            return
        if not (isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return
        if node.attr not in self.scope.guards:
            return
        is_write = isinstance(node.ctx, (ast.Store, ast.Del))
        self._attr_access(node.attr, node, is_write, f"self.{node.attr}")

    def _check_global(self, node: ast.Name) -> None:
        if not self.scope.is_module or node.id not in self.scope.guards:
            return
        is_write = isinstance(node.ctx, (ast.Store, ast.Del))
        self._attr_access(node.id, node, is_write, node.id)

    def _check_check_then_act(self, attr: str, lock: str,
                              node: ast.AST) -> None:
        prior = self.reads.get(attr)
        if prior is None:
            return
        read_sess, read_lock = prior
        cur_sess = self.session_of.get(lock, 0)
        if read_lock != lock or read_sess == cur_sess:
            return
        # The lock was released and re-acquired between the read and this
        # write. Only flag when the write actually depends on a stale
        # local from that earlier session (condition or value).
        stale = {name for name, (a, sess) in self.tainted.items()
                 if a == attr and sess == read_sess}
        if not stale:
            return
        cond = set().union(*self.cond_names) if self.cond_names else set()
        if stale & cond:
            self._report(
                node, "check-then-act", attr,
                f"self.{attr} written under a re-acquired {lock} based "
                f"on a value read in an earlier critical section "
                f"({', '.join(sorted(stale & cond))} escaped the lock)")

    # -- blocking calls ------------------------------------------------
    def _check_call(self, node: ast.Call) -> None:
        if not self.held:
            return
        func = node.func
        if not isinstance(func, ast.Attribute):
            return
        recv_text = _unparse(func.value)
        name = func.attr
        blocking = False
        subject = f"{recv_text}.{name}"
        if name in _BLOCKING_METHODS:
            blocking = True
        elif isinstance(func.value, ast.Name) \
                and (func.value.id, name) in _BLOCKING_DOTTED:
            blocking = True
        elif name in _INDEFINITE_METHODS:
            # ev.wait() / t.join() with no timeout parks forever; a
            # cv-style wait on a lock we HOLD is the release-and-wait
            # pattern and is fine
            if recv_text in self.held:
                return
            has_timeout = bool(node.args) or any(
                kw.arg in ("timeout",) and not (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value is None)
                for kw in node.keywords)
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and node.args[0].value is None:
                has_timeout = False
            blocking = not has_timeout
        if blocking:
            self._report(
                node, "blocking-under-lock", subject,
                f"blocking call {subject}(...) while holding "
                f"{', '.join(sorted(set(self.held)))}")


class _Analyzer:
    def __init__(self, path: str, source: str) -> None:
        self.path = path
        self.source_lines = source.splitlines()
        self.pragmas = _Pragmas(source)
        self.tree = ast.parse(source)
        self.findings: list[Finding] = []
        self._queue: list[tuple[ast.AST, _Scope, str, frozenset[str]]] = []

    def report(self, node: ast.AST, rule: str, qualname: str,
               subject: str, message: str) -> None:
        if self.pragmas.suppressed(node, rule):
            return
        self.findings.append(Finding(
            path=self.path, line=getattr(node, "lineno", 0), rule=rule,
            qualname=qualname, subject=subject, message=message))

    def queue_function(self, node, scope: _Scope, qualname: str,
                       assume: frozenset[str]) -> None:
        self._queue.append((node, scope, qualname, assume))

    # -- discovery -----------------------------------------------------
    def run(self) -> list[Finding]:
        module_guards = self._module_guard_map()
        mod_scope = _Scope("<module>", module_guards, is_module=True)
        for stmt in self.tree.body:
            if isinstance(stmt, ast.ClassDef):
                self.visit_class(stmt, parent=None)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.queue_function(stmt, mod_scope, stmt.name,
                                    frozenset())
        while self._queue:
            node, scope, qualname, assume = self._queue.pop()
            if self.pragmas.suppressed_def(node):
                continue
            w = _FunctionWalker(self, scope, qualname, assume)
            w.walk_body(node.body)
        return self.findings

    def _module_guard_map(self) -> dict[str, str]:
        guards: dict[str, str] = {}
        for stmt in self.tree.body:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                guards.update(_literal_guard_map(stmt))
        guards.update(_comment_guards(self.tree.body, self.source_lines,
                                      self_name=None))
        return guards

    def visit_class(self, node: ast.ClassDef, parent: str | None) -> None:
        qual = f"{parent}.{node.name}" if parent else node.name
        guards: dict[str, str] = {}
        init: ast.FunctionDef | None = None
        for stmt in node.body:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                guards.update(_literal_guard_map(stmt))
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and stmt.name == "__init__":
                init = stmt
        if init is not None:
            guards.update(_comment_guards(init.body, self.source_lines,
                                          self_name="self"))
        scope = _Scope(qual, guards, is_module=False)
        for stmt in node.body:
            if isinstance(stmt, ast.ClassDef):
                self.visit_class(stmt, parent=qual)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if stmt.name in ("__init__", "__new__", "__del__"):
                    continue  # pre-publication / teardown: not shared
                assume: frozenset[str] = frozenset()
                if stmt.name.endswith("_locked"):
                    # Repo convention: *_locked helpers document "caller
                    # holds the lock" — assume every class guard is held
                    assume = frozenset(scope.all_locks)
                self.queue_function(stmt, scope, f"{qual}.{stmt.name}",
                                    assume)


def analyze_source(source: str, path: str = "<string>") -> list[Finding]:
    return _Analyzer(path, source).run()


def analyze_file(file_path: str, rel_path: str | None = None
                 ) -> list[Finding]:
    with open(file_path, encoding="utf-8") as f:
        source = f.read()
    return analyze_source(source, rel_path or file_path)


def analyze_paths(root: str, subdirs: tuple[str, ...] = ("faabric_tpu",)
                  ) -> list[Finding]:
    findings: list[Finding] = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, root)
                try:
                    findings.extend(analyze_file(full, rel))
                except SyntaxError as e:  # pragma: no cover
                    findings.append(Finding(
                        path=rel, line=e.lineno or 0, rule="parse-error",
                        qualname="<module>", subject="syntax",
                        message=str(e)))
    return findings
