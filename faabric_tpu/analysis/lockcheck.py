"""Opt-in runtime lock-order / hold-time / blocking-syscall detector.

``FAABRIC_LOCKCHECK=1`` (installed by ``tests/conftest.py`` before any
faabric module loads) replaces the ``threading.Lock``/``threading.RLock``
factories with wrappers that:

- build a **held-before graph**: acquiring lock B while holding lock A
  records the edge ``site(A) → site(B)`` (sites are the ``Lock()``
  creation points, ``file:line`` — instances pool by site so the graph
  stays small and cycles across *instances* of the same classes are
  caught). ``report()`` runs cycle detection; each cycle carries the
  holder's acquire point and the full acquisition stack of the edge that
  closed it — the two stacks a deadlock post-mortem needs.
- record **hold times** per site into the telemetry registry
  (``faabric_lock_hold_seconds{site=...}``), so ``/metrics`` shows which
  critical sections are long and bench rounds can track them.
- report **locks held across blocking syscalls**: ``time.sleep``,
  ``threading.Event.wait`` and the socket primitives are patched to note
  when the calling thread holds any checked lock (rule the static lint
  enforces too — this catches the paths the lint cannot see, e.g. calls
  through ctypes or dynamically-dispatched handlers).

Scope: only locks *created* from files under ``faabric_tpu/`` or
``tests/`` are wrapped (``FAABRIC_LOCKCHECK_ALL=1`` wraps everything) —
wrapping JAX/XLA's internal locks would only add noise and overhead.
Locks created before ``install()`` stay plain; the detector is a test
instrument, not a safety net.

Same-site nesting (two *instances* from one creation site nested in one
thread) is reported separately from cycles: it is only a deadlock if
another thread nests them in the opposite order, which a site-keyed
graph cannot order — the report names it so a reviewer can impose an
ordering discipline.

Everything here must be reentrancy-safe: internal state uses the
*original* lock type, and no code path logs or allocates telemetry
handles while holding the internal lock.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Optional

__all__ = [
    "install", "installed", "enabled_by_env", "report", "format_report",
    "reset", "CheckedLockFactory",
]

_REPO_MARKERS = (f"faabric_tpu{os.sep}", f"tests{os.sep}")


def _is_internal_frame(fn: str) -> bool:
    # Exact basename match: endswith() would also skip any caller file
    # that merely ENDS with these names (test_lockcheck.py!)
    return os.path.basename(fn) in ("lockcheck.py", "threading.py")

_orig_lock = threading.Lock
_orig_rlock = threading.RLock

_STACK_DEPTH = int(os.environ.get("FAABRIC_LOCKCHECK_STACK_DEPTH", "10"))
_MAX_BLOCKING_REPORTS = 500


class _State:
    def __init__(self) -> None:
        self.mx = _orig_lock()
        # site id → "file:line"
        self.sites: dict[int, str] = {}
        self.site_ids: dict[str, int] = {}
        # (site_a, site_b) → (holder acquire point, acquiring stack)
        self.edges: dict[tuple[int, int], tuple[str, tuple[str, ...]]] = {}
        # same-site nesting: site → (holder point, acquiring stack)
        self.same_site: dict[int, tuple[str, tuple[str, ...]]] = {}
        # blocking-call-under-lock reports
        self.blocking: list[dict] = []
        # site id → telemetry Histogram (created lazily OUTSIDE self.mx)
        self.hold_hist: dict[int, object] = {}

    def site_id(self, site: str) -> int:
        with self.mx:
            sid = self.site_ids.get(site)
            if sid is None:
                sid = len(self.site_ids) + 1
                self.site_ids[site] = sid
                self.sites[sid] = site
            return sid


_state = _State()
_installed = False
_tls = threading.local()


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _creation_site() -> str | None:
    """Creation point of the lock (first frame outside this module and
    the threading module), or None when the creator is out of scope —
    telemetry's per-series leaf locks are always exempt (the hold-time
    observer itself takes them; wrapping them would both recurse and
    drown the graph in per-counter edges)."""
    wrap_all = os.environ.get("FAABRIC_LOCKCHECK_ALL", "0") == "1"
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if not _is_internal_frame(fn):
            if f"faabric_tpu{os.sep}telemetry{os.sep}" in fn:
                return None
            if not wrap_all and not any(m in fn for m in _REPO_MARKERS):
                return None
            return f"{os.path.basename(os.path.dirname(fn))}/" \
                   f"{os.path.basename(fn)}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>" if wrap_all else None


def _short_stack(limit: int = _STACK_DEPTH) -> tuple[str, ...]:
    out = []
    f = sys._getframe(2)
    while f is not None and len(out) < limit:
        fn = f.f_code.co_filename
        if os.path.basename(fn) != "lockcheck.py":
            out.append(f"{fn}:{f.f_lineno} in {f.f_code.co_name}")
        f = f.f_back
    return tuple(out)


class _Entry:
    __slots__ = ("obj_id", "sid", "t0", "count", "frame")

    def __init__(self, obj_id: int, sid: int, t0: float, frame) -> None:
        self.obj_id = obj_id
        self.sid = sid
        self.t0 = t0
        self.count = 1
        # Raw frame of the acquire, formatted lazily — only edges and
        # reports pay the string cost, never the per-acquire hot path
        self.frame = frame

    def point(self) -> str:
        f = self.frame
        while f is not None:
            fn = f.f_code.co_filename
            if not _is_internal_frame(fn):
                return f"{fn}:{f.f_lineno}"
            f = f.f_back
        return "<unknown>"


def _note_acquire(obj_id: int, sid: int) -> None:
    held = _held()
    for e in held:
        if e.obj_id == obj_id:
            e.count += 1  # RLock re-entry: no edge, no new entry
            return
    if held:
        stack = None
        for e in held:
            key = (e.sid, sid)
            if e.sid == sid:
                if sid not in _state.same_site:
                    if stack is None:
                        stack = _short_stack()
                    with _state.mx:
                        _state.same_site.setdefault(
                            sid, (e.point(), stack))
                continue
            if key not in _state.edges:
                if stack is None:
                    stack = _short_stack()
                with _state.mx:
                    _state.edges.setdefault(key, (e.point(), stack))
    held.append(_Entry(obj_id, sid, time.monotonic(), sys._getframe(2)))


def _note_release(obj_id: int, sid: int) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        e = held[i]
        if e.obj_id == obj_id:
            e.count -= 1
            if e.count <= 0:
                del held[i]
                _observe_hold(sid, time.monotonic() - e.t0)
            return


def _observe_hold(sid: int, seconds: float) -> None:
    # Reentrancy guard: the observe itself takes (possibly checked)
    # telemetry locks whose release would land back here
    if getattr(_tls, "in_observe", False):
        return
    _tls.in_observe = True
    try:
        hist = _state.hold_hist.get(sid)
        if hist is None:
            try:
                from faabric_tpu.telemetry import get_metrics

                hist = get_metrics().histogram(
                    "faabric_lock_hold_seconds",
                    "Lock hold time per creation site "
                    "(FAABRIC_LOCKCHECK=1)",
                    site=_state.sites.get(sid, "?"))
            except Exception:  # pragma: no cover - telemetry unavailable
                hist = None
            _state.hold_hist[sid] = hist
        if hist is not None:
            hist.observe(seconds)
    finally:
        _tls.in_observe = False


class _CheckedLock:
    """threading.Lock wrapper; also the base for the RLock wrapper."""

    _reentrant = False

    def __init__(self, inner, sid: int) -> None:
        self._inner = inner
        self._sid = sid

    # -- core protocol -------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        rc = self._inner.acquire(blocking, timeout)
        if rc:
            _note_acquire(id(self._inner), self._sid)
        return rc

    acquire_lock = acquire  # legacy alias some libraries use

    def release(self) -> None:
        self._inner.release()
        _note_release(id(self._inner), self._sid)

    release_lock = release

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        site = _state.sites.get(self._sid, "?")
        return f"<CheckedLock {site} wrapping {self._inner!r}>"


class _CheckedRLock(_CheckedLock):
    _reentrant = True

    # Condition-variable protocol: Condition(lock) probes for these and
    # uses them to fully release a reentrant lock around wait(). The
    # held-tracking must follow, or the detector would see the lock as
    # held across the (legitimate) blocking wait.
    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        state = self._inner._release_save()
        _note_release(id(self._inner), self._sid)
        return state

    def _acquire_restore(self, state) -> None:
        self._inner._acquire_restore(state)
        _note_acquire(id(self._inner), self._sid)


class CheckedLockFactory:
    """Callable drop-in for ``threading.Lock``/``threading.RLock``.

    ``force_site`` bypasses the caller-scope check and stamps every
    created lock with the given site label — for benches and tests that
    live outside faabric_tpu/ or tests/ but want a checked lock."""

    def __init__(self, reentrant: bool,
                 force_site: str | None = None) -> None:
        self._reentrant = reentrant
        self._force_site = force_site

    def __call__(self):
        site = self._force_site or _creation_site()
        if site is None:
            return (_orig_rlock if self._reentrant else _orig_lock)()
        sid = _state.site_id(site)
        if self._reentrant:
            return _CheckedRLock(_orig_rlock(), sid)
        return _CheckedLock(_orig_lock(), sid)


# ---------------------------------------------------------------------------
# Blocking-syscall instrumentation
# ---------------------------------------------------------------------------

def _note_blocking(what: str, detail: str = "") -> None:
    held = getattr(_tls, "held", None)
    if not held:
        return
    sites = [_state.sites.get(e.sid, "?") for e in held]
    stack = _short_stack()
    with _state.mx:
        if len(_state.blocking) < _MAX_BLOCKING_REPORTS:
            _state.blocking.append({
                "call": what, "detail": detail, "held": sites,
                "stack": stack,
                "thread": threading.current_thread().name,
            })


def _wrap_blocking(orig, what: str):
    def wrapper(*args, **kwargs):
        held = getattr(_tls, "held", None)
        if held:
            _note_blocking(what)
        return orig(*args, **kwargs)

    wrapper.__name__ = getattr(orig, "__name__", what)
    wrapper.__qualname__ = wrapper.__name__
    return wrapper


def _patch_blocking_calls() -> None:
    import socket as socket_mod

    time.sleep = _wrap_blocking(time.sleep, "time.sleep")

    ev_wait = threading.Event.wait

    def event_wait(self, timeout: Optional[float] = None):
        if getattr(_tls, "held", None):
            _note_blocking("Event.wait",
                           "indefinite" if timeout is None
                           else f"timeout={timeout}")
        return ev_wait(self, timeout)

    threading.Event.wait = event_wait  # type: ignore[method-assign]

    th_join = threading.Thread.join

    def thread_join(self, timeout: Optional[float] = None):
        if getattr(_tls, "held", None):
            _note_blocking("Thread.join",
                           "indefinite" if timeout is None
                           else f"timeout={timeout}")
        return th_join(self, timeout)

    threading.Thread.join = thread_join  # type: ignore[method-assign]

    # socket.socket is a Python subclass of the C _socket.socket, so
    # method overrides stick. Only note-and-delegate — never alter
    # semantics.
    for name in ("accept", "connect", "recv", "recv_into", "recvfrom",
                 "send", "sendall", "sendmsg"):
        base = getattr(socket_mod.socket, name, None)
        if base is None:  # pragma: no cover - platform-dependent
            continue

        def make(nm, fn):
            def sock_wrapper(self, *args, **kwargs):
                if getattr(_tls, "held", None):
                    _note_blocking(f"socket.{nm}")
                return fn(self, *args, **kwargs)

            sock_wrapper.__name__ = nm
            return sock_wrapper

        setattr(socket_mod.socket, name, make(name, base))


# ---------------------------------------------------------------------------
# Install / report
# ---------------------------------------------------------------------------

def enabled_by_env() -> bool:
    return os.environ.get("FAABRIC_LOCKCHECK", "0") not in (
        "0", "", "false", "off")


def installed() -> bool:
    return _installed


def install() -> None:
    """Patch the lock factories and blocking syscalls. Idempotent.
    Locks created before this call stay plain."""
    global _installed
    if _installed:
        return
    _installed = True
    threading.Lock = CheckedLockFactory(reentrant=False)
    threading.RLock = CheckedLockFactory(reentrant=True)
    _patch_blocking_calls()


def reset() -> None:
    """Drop collected graph/report state (tests)."""
    with _state.mx:
        _state.edges.clear()
        _state.same_site.clear()
        _state.blocking.clear()


def _find_cycles(edges: dict) -> list[list[int]]:
    graph: dict[int, set[int]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    cycles: list[list[int]] = []
    seen_cycles: set[tuple[int, ...]] = set()

    # Iterative DFS per start node; small graphs (~dozens of sites)
    for start in list(graph):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in graph.get(node, ()):
                if nxt == start:
                    cyc = path[:]
                    key = tuple(sorted(cyc))
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        cycles.append(cyc)
                elif nxt not in path and len(path) < 12:
                    stack.append((nxt, path + [nxt]))
    return cycles


def report() -> dict:
    """Snapshot of everything collected so far."""
    with _state.mx:
        edges = dict(_state.edges)
        same_site = dict(_state.same_site)
        blocking = list(_state.blocking)
        sites = dict(_state.sites)

    cycles = []
    for cyc in _find_cycles(edges):
        detail = []
        for i, sid in enumerate(cyc):
            nxt = cyc[(i + 1) % len(cyc)]
            holder_point, acq_stack = edges.get(
                (sid, nxt), ("?", ()))
            detail.append({
                "held": sites.get(sid, "?"),
                "then_acquired": sites.get(nxt, "?"),
                "holder_acquired_at": holder_point,
                "acquisition_stack": list(acq_stack),
            })
        cycles.append(detail)

    return {
        "sites": len(sites),
        "edges": [
            {"held": sites.get(a, "?"), "then": sites.get(b, "?"),
             "holder_acquired_at": point}
            for (a, b), (point, _stack) in sorted(edges.items())
        ],
        "cycles": cycles,
        "same_site_nesting": [
            {"site": sites.get(sid, "?"), "holder_acquired_at": point,
             "acquisition_stack": list(stack)}
            for sid, (point, stack) in sorted(same_site.items())
        ],
        "blocking_under_lock": blocking,
    }


def format_report(rep: Optional[dict] = None) -> str:
    rep = rep if rep is not None else report()
    lines = [
        f"lockcheck: {rep['sites']} checked lock sites, "
        f"{len(rep['edges'])} held-before edges, "
        f"{len(rep['cycles'])} potential-deadlock cycle(s), "
        f"{len(rep['same_site_nesting'])} same-site nesting(s), "
        f"{len(rep['blocking_under_lock'])} blocking-call-under-lock "
        f"report(s)"
    ]
    for cyc in rep["cycles"]:
        lines.append("  POTENTIAL DEADLOCK CYCLE:")
        for hop in cyc:
            lines.append(f"    {hop['held']} (acquired at "
                         f"{hop['holder_acquired_at']}) -> "
                         f"{hop['then_acquired']}")
            for fr in hop["acquisition_stack"][:6]:
                lines.append(f"        {fr}")
    for ss in rep["same_site_nesting"]:
        lines.append(f"  same-site nesting: {ss['site']} "
                     f"(holder acquired at {ss['holder_acquired_at']}) — "
                     f"needs an instance-ordering discipline")
    for b in rep["blocking_under_lock"][:20]:
        lines.append(f"  blocking under lock: {b['call']} "
                     f"({b['detail']}) holding {b['held']} "
                     f"[{b['thread']}]")
        for fr in b["stack"][:4]:
            lines.append(f"        {fr}")
    return "\n".join(lines)
