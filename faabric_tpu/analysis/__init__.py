"""Concurrency conformance suite (ISSUE 7).

Three machine-checks over the runtime's 70+ lock sites and ~20 daemon
threads, each catching a bug class that PRs 4-6 kept surfacing by hand:

- ``guards``   — AST guarded-by lint: shared attributes declare their
  lock (``GUARDS`` class map or a ``# guard: self._lock`` trailing
  comment) and every access is verified to happen inside the matching
  ``with`` scope; also flags check-then-act escapes and known-blocking
  calls made while a lock is held.
- ``protodrift`` — static protocol-drift pass: every RPC call-enum
  member has a registered server handler, and every call-site uses a
  declared member.
- ``lockcheck`` — opt-in runtime detector (``FAABRIC_LOCKCHECK=1``):
  instrumented Lock/RLock wrappers build a held-before graph with cycle
  detection, record per-site hold-time histograms into the telemetry
  registry, and report locks held across blocking syscalls.

``tools/concheck.py`` runs the static passes against the committed
baseline (``tools/concheck_baseline.txt``) in the same ratchet style as
``tools/failure_gate.py``. See docs/static_analysis.md.

This package imports nothing heavy at module scope: ``lockcheck`` must
be installable before JAX (or anything else that creates locks) loads.
"""
