"""Input pipeline: memmap token datasets + prefetching mesh loaders."""

from faabric_tpu.data.loader import DataLoader, TokenDataset

__all__ = ["DataLoader", "TokenDataset"]
