"""Input pipeline: token datasets and a prefetching loader for meshes.

The runtime-side IO piece of the framework (the reference's native
data-path analog — there it is C++ queues feeding executors; here the
host loader feeds chips): a memmap-backed token store, deterministic
shuffled windows, and a background thread that stages the NEXT batch
onto the devices (dp-sharded) while the current step runs, so input IO
overlaps compute instead of serializing with it.

Usage::

    ds = TokenDataset.from_file("corpus.bin", seq_len=2048)  # or from array
    loader = DataLoader(ds, batch_size=32, mesh=mesh, seed=0)
    for tokens, targets in loader:          # device-resident, dp-sharded
        params, opt_state, loss = step(params, opt_state, tokens, targets)
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional

import numpy as np

from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)


class TokenDataset:
    """Contiguous token ids carved into (seq_len + 1) windows: a window
    yields (inputs = w[:-1], targets = w[1:])."""

    def __init__(self, tokens: np.ndarray, seq_len: int) -> None:
        if tokens.ndim != 1:
            raise ValueError("TokenDataset wants a flat token id array")
        self.tokens = tokens
        self.seq_len = int(seq_len)
        self.n_windows = (tokens.size - 1) // self.seq_len
        if self.n_windows <= 0:
            raise ValueError(
                f"{tokens.size} tokens cannot fill a {seq_len}-token window")

    @classmethod
    def from_file(cls, path: str, seq_len: int,
                  dtype=np.int32) -> "TokenDataset":
        """Zero-copy memmap over a flat binary token file — corpora far
        larger than RAM stream through the page cache."""
        return cls(np.memmap(path, dtype=dtype, mode="r"), seq_len)

    def window(self, idx: int) -> tuple[np.ndarray, np.ndarray]:
        lo = idx * self.seq_len
        w = np.asarray(self.tokens[lo:lo + self.seq_len + 1])
        return w[:-1], w[1:]

    def __len__(self) -> int:
        return self.n_windows


class DataLoader:
    """Batches of shuffled windows, staged onto the mesh one batch ahead.

    Deterministic per (seed, epoch): every rank/process computes the same
    permutation, so multi-host data parallelism can slice the same order
    by dp coordinate without coordination traffic.
    """

    def __init__(self, dataset: TokenDataset, batch_size: int,
                 mesh=None, seed: int = 0, drop_last: bool = True,
                 prefetch: int = 2) -> None:
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.mesh = mesh
        self.seed = seed
        self.drop_last = drop_last
        self.prefetch = max(1, int(prefetch))
        if drop_last and len(dataset) < batch_size:
            raise ValueError(
                f"{len(dataset)} windows < batch_size {batch_size}")
        if mesh is not None:
            dp = mesh.shape.get("dp", 1)
            if batch_size % dp:
                raise ValueError(
                    f"batch_size {batch_size} not divisible by dp={dp}")
            if not drop_last:
                raise ValueError(
                    "drop_last=False cannot shard a partial final batch "
                    "over the mesh; use drop_last=True")
        self._epoch = 0

    # -- assembly -------------------------------------------------------
    def _batch_indices(self, epoch: int):
        rng = np.random.RandomState((self.seed * 1_000_003 + epoch)
                                    & 0x7FFFFFFF)
        order = rng.permutation(len(self.dataset))
        stop = (len(order) - len(order) % self.batch_size
                if self.drop_last else len(order))
        for lo in range(0, stop, self.batch_size):
            yield order[lo:lo + self.batch_size]

    def _assemble(self, idxs: np.ndarray):
        xs = np.empty((len(idxs), self.dataset.seq_len), np.int32)
        ys = np.empty_like(xs)
        for i, w in enumerate(idxs):
            x, y = self.dataset.window(int(w))
            xs[i], ys[i] = x, y
        if self.mesh is None:
            return xs, ys
        import jax

        from faabric_tpu.models.train import data_sharding

        sharding = data_sharding(self.mesh)
        return (jax.device_put(xs, sharding), jax.device_put(ys, sharding))

    # -- iteration ------------------------------------------------------
    def __iter__(self) -> Iterator:
        """One epoch, prefetched: a daemon worker assembles + device_puts
        the next batches while the caller consumes the current one."""
        epoch, self._epoch = self._epoch, self._epoch + 1
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        _END = object()

        def put(item) -> bool:
            # Bounded put that gives up when the consumer abandoned the
            # epoch (break/exception) — otherwise the thread would park
            # in q.put forever, pinning staged device batches
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for idxs in self._batch_indices(epoch):
                    if stop.is_set() or not put(self._assemble(idxs)):
                        return
            except Exception as e:  # noqa: BLE001 — surfaced to consumer
                put(e)
            finally:
                put(_END)

        t = threading.Thread(target=producer, name="data/prefetch",
                             daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    return
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass

    def __len__(self) -> int:
        n = len(self.dataset)
        return n // self.batch_size if self.drop_last else \
            -(-n // self.batch_size)
