"""Snapshots: typed merge regions, dirty diffs, registry, RPC
(reference src/snapshot + src/util/snapshot.cpp)."""

from faabric_tpu.snapshot.snapshot import (
    DIFF_CHUNK,
    MergeRegion,
    SnapshotData,
    SnapshotDataType,
    SnapshotDiff,
    SnapshotMergeOperation,
)
from faabric_tpu.snapshot.device_snapshot import (
    DEVICE_PAGE_SIZE,
    DeviceSnapshot,
)
from faabric_tpu.snapshot.registry import SnapshotRegistry
from faabric_tpu.snapshot.remote import (
    SnapshotCalls,
    SnapshotClient,
    SnapshotServer,
    clear_mock_snapshot_requests,
    get_mock_thread_results,
    get_snapshot_diff_pushes,
    get_snapshot_pushes,
)

__all__ = [
    "DEVICE_PAGE_SIZE",
    "DIFF_CHUNK",
    "DeviceSnapshot",
    "MergeRegion",
    "SnapshotCalls",
    "SnapshotClient",
    "SnapshotData",
    "SnapshotDataType",
    "SnapshotDiff",
    "SnapshotMergeOperation",
    "SnapshotRegistry",
    "SnapshotServer",
    "clear_mock_snapshot_requests",
    "get_mock_thread_results",
    "get_snapshot_diff_pushes",
    "get_snapshot_pushes",
]
