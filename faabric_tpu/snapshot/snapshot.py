"""Snapshots with typed merge regions and dirty-region diffing.

Reference analog: include/faabric/util/snapshot.h:27-345 and
src/util/snapshot.cpp (825 lines). A snapshot is a byte image of executor
memory plus **merge regions** describing how concurrent writers reconcile:
bytewise overwrite, arithmetic merges (sum/product/subtract/max/min over
int/long/float/double values), XOR, or ignore.

Diffing walks the dirty pages (util/dirty.py) through the merge regions:
arithmetic regions emit elementwise *deltas* (vectorised numpy — e.g. a
Sum region's diff is ``updated - original`` so applying adds the writer's
contribution), bytewise gaps emit changed byte ranges at 128-byte chunk
granularity (reference snapshot.h:18-21), using the native C++ range
scanner when available.

The reference mmaps guest memory; here images are numpy buffers — the
device analog is a ``jax.device_get`` of HBM state into the image, with
restore as ``device_put`` (checkpoint/resume rides the same machinery).
"""

from __future__ import annotations

import dataclasses
import enum
import threading
import time
from typing import Iterable, Optional

import numpy as np

from faabric_tpu.telemetry.statestats import get_state_stats
from faabric_tpu.util.dirty import PAGE_SIZE, n_pages
from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

# Byte-chunk granularity for bytewise diffs (reference snapshot.h:18-21)
DIFF_CHUNK = 128


class SnapshotDataType(enum.IntEnum):
    RAW = 0
    BOOL = 1
    INT = 2
    LONG = 3
    FLOAT = 4
    DOUBLE = 5


_NP_TYPES = {
    SnapshotDataType.BOOL: np.dtype(np.uint8),
    SnapshotDataType.INT: np.dtype(np.int32),
    SnapshotDataType.LONG: np.dtype(np.int64),
    SnapshotDataType.FLOAT: np.dtype(np.float32),
    SnapshotDataType.DOUBLE: np.dtype(np.float64),
}


class SnapshotMergeOperation(enum.IntEnum):
    BYTEWISE = 0
    SUM = 1
    PRODUCT = 2
    SUBTRACT = 3
    MAX = 4
    MIN = 5
    IGNORE = 6
    XOR = 7


@dataclasses.dataclass(frozen=True)
class MergeRegion:
    offset: int
    length: int
    data_type: SnapshotDataType = SnapshotDataType.RAW
    operation: SnapshotMergeOperation = SnapshotMergeOperation.BYTEWISE

    @property
    def end(self) -> int:
        return self.offset + self.length

    def to_dict(self) -> dict:
        return {"offset": self.offset, "length": self.length,
                "data_type": int(self.data_type),
                "operation": int(self.operation)}

    @classmethod
    def from_dict(cls, d: dict) -> "MergeRegion":
        return cls(d["offset"], d["length"],
                   SnapshotDataType(d.get("data_type", 0)),
                   SnapshotMergeOperation(d.get("operation", 0)))


@dataclasses.dataclass
class SnapshotDiff:
    offset: int
    data: bytes
    data_type: SnapshotDataType = SnapshotDataType.RAW
    operation: SnapshotMergeOperation = SnapshotMergeOperation.BYTEWISE

    def to_dict(self) -> dict:
        # data rides the RPC binary tail, keyed by length
        return {"offset": self.offset, "length": len(self.data),
                "data_type": int(self.data_type),
                "operation": int(self.operation)}


class SnapshotData:
    def __init__(self, data: bytes | bytearray | np.ndarray | int,
                 max_size: int = 0) -> None:
        if isinstance(data, int):
            self._data = np.zeros(data, dtype=np.uint8)
        else:
            self._data = np.frombuffer(bytes(data), dtype=np.uint8).copy()
        self.max_size = max(max_size, self._data.size)
        self._lock = threading.RLock()
        self._merge_regions: list[MergeRegion] = []
        self._queued_diffs: list[SnapshotDiff] = []

    # ------------------------------------------------------------------
    @property
    def size(self) -> int:
        return self._data.size

    @property
    def data(self) -> np.ndarray:
        return self._data

    def to_bytes(self) -> bytes:
        return self._data.tobytes()

    def resize(self, new_size: int) -> None:
        if new_size > self.max_size:
            raise ValueError(f"Snapshot resize {new_size} > max {self.max_size}")
        with self._lock:
            if new_size > self._data.size:
                self._data = np.concatenate(
                    [self._data,
                     np.zeros(new_size - self._data.size, np.uint8)])
            else:
                self._data = self._data[:new_size].copy()

    # ------------------------------------------------------------------
    # Merge regions
    # ------------------------------------------------------------------
    def add_merge_region(self, offset: int, length: int,
                         data_type: SnapshotDataType = SnapshotDataType.RAW,
                         operation: SnapshotMergeOperation =
                         SnapshotMergeOperation.BYTEWISE) -> None:
        if operation != SnapshotMergeOperation.BYTEWISE \
                and operation != SnapshotMergeOperation.IGNORE \
                and operation != SnapshotMergeOperation.XOR:
            width = _NP_TYPES[data_type].itemsize
            if length % width != 0:
                raise ValueError(
                    f"Merge region length {length} not a multiple of "
                    f"{data_type.name} width {width}")
        with self._lock:
            self._merge_regions.append(MergeRegion(offset, length,
                                                   data_type, operation))
            self._merge_regions.sort(key=lambda r: r.offset)

    def get_merge_regions(self) -> list[MergeRegion]:
        with self._lock:
            return list(self._merge_regions)

    def clear_merge_regions(self) -> None:
        with self._lock:
            self._merge_regions.clear()

    def fill_gaps_with_bytewise_regions(self) -> None:
        """Cover the whole image: unclaimed ranges become bytewise regions
        (reference fillGapsWithBytewiseRegions)."""
        with self._lock:
            regions = sorted(self._merge_regions, key=lambda r: r.offset)
            gaps: list[MergeRegion] = []
            cursor = 0
            for r in regions:
                if r.offset > cursor:
                    gaps.append(MergeRegion(cursor, r.offset - cursor))
                cursor = max(cursor, r.end)
            if cursor < self.size:
                gaps.append(MergeRegion(cursor, self.size - cursor))
            self._merge_regions.extend(gaps)
            self._merge_regions.sort(key=lambda r: r.offset)

    # ------------------------------------------------------------------
    # Diffing
    # ------------------------------------------------------------------
    def diff_with_dirty_regions(self, mem, dirty_pages: np.ndarray
                                ) -> list[SnapshotDiff]:
        """Diff updated memory against this snapshot over the dirty pages,
        honouring merge regions (reference diffWithDirtyRegions)."""
        stats = get_state_stats()
        t0 = time.perf_counter() if stats.enabled else 0.0
        cur = np.frombuffer(mem, dtype=np.uint8)
        diffs: list[SnapshotDiff] = []
        if not dirty_pages.any():
            return diffs

        # Dirty byte ranges from page flags, over the FULL current memory
        # (writes beyond the snapshot's size become extension diffs)
        dirty_ranges = _pages_to_ranges(dirty_pages, cur.size)

        with self._lock:
            regions = list(self._merge_regions)
        if not regions:
            regions = [MergeRegion(0, self.size)]

        # Memory grown past the snapshot: emit the dirty part of the
        # extension as raw bytewise data (reference diffWithDirtyRegions
        # emits the extended region explicitly)
        if cur.size > self.size:
            for start, end in dirty_ranges:
                lo = max(start, self.size)
                if lo < end:
                    diffs.append(SnapshotDiff(lo, cur[lo:end].tobytes()))

        for start, end in dirty_ranges:
            end = min(end, self.size)
            for region in regions:
                lo = max(start, region.offset)
                hi = min(end, region.end)
                if lo >= hi:
                    continue
                op = region.operation
                if op == SnapshotMergeOperation.IGNORE:
                    continue
                if op == SnapshotMergeOperation.BYTEWISE:
                    diffs.extend(self._bytewise_diffs(cur, lo, hi))
                elif op == SnapshotMergeOperation.XOR:
                    old = self._data[lo:hi]
                    new = cur[lo:hi]
                    if not np.array_equal(old, new):
                        diffs.append(SnapshotDiff(
                            lo, np.bitwise_xor(old, new).tobytes(),
                            region.data_type, op))
                else:
                    # Arithmetic region: align to the region's value grid
                    # and emit an elementwise delta for the whole region
                    d = self._arith_diff(cur, region)
                    if d is not None and not any(
                            x.offset == region.offset and x.operation == op
                            for x in diffs):
                        diffs.append(d)
        if stats.enabled:
            stats.snapshot_event(
                "diff", nbytes=sum(len(d.data) for d in diffs),
                pages=int(dirty_pages.sum()), regions=len(regions),
                seconds=time.perf_counter() - t0)
        return diffs

    def _bytewise_diffs(self, cur: np.ndarray, lo: int, hi: int
                        ) -> Iterable[SnapshotDiff]:
        from faabric_tpu.util.native import get_pagediff_lib

        old = np.ascontiguousarray(self._data[lo:hi])
        new = np.ascontiguousarray(cur[lo:hi])
        length = hi - lo
        lib = get_pagediff_lib()
        out = []
        if lib is not None:
            max_ranges = max(4, length // DIFF_CHUNK + 1)
            starts = np.zeros(max_ranges, dtype=np.uintp)
            lengths = np.zeros(max_ranges, dtype=np.uintp)
            n = lib.diff_ranges(old.ctypes.data, new.ctypes.data, length,
                                DIFF_CHUNK, starts.ctypes.data,
                                lengths.ctypes.data, max_ranges)
            for i in range(n):
                s, l = int(starts[i]), int(lengths[i])
                out.append(SnapshotDiff(lo + s, new[s:s + l].tobytes()))
            return out
        # numpy fallback: chunked compare
        n_chunks = (length + DIFF_CHUNK - 1) // DIFF_CHUNK
        run_start = None
        for c in range(n_chunks + 1):
            s = c * DIFF_CHUNK
            e = min(length, s + DIFF_CHUNK)
            differs = (c < n_chunks
                       and not np.array_equal(old[s:e], new[s:e]))
            if differs and run_start is None:
                run_start = s
            elif not differs and run_start is not None:
                out.append(SnapshotDiff(lo + run_start,
                                        new[run_start:s].tobytes()))
                run_start = None
        return out

    def _arith_diff(self, cur: np.ndarray,
                    region: MergeRegion) -> Optional[SnapshotDiff]:
        dtype = _NP_TYPES[region.data_type]
        lo, hi = region.offset, min(region.end, cur.size, self.size)
        old = self._data[lo:hi].view(dtype)
        new = cur[lo:hi].view(dtype)
        if np.array_equal(old, new):
            return None
        op = region.operation
        if op == SnapshotMergeOperation.SUM:
            delta = new - old
        elif op == SnapshotMergeOperation.SUBTRACT:
            delta = old - new
        elif op == SnapshotMergeOperation.PRODUCT:
            with np.errstate(divide="ignore", invalid="ignore"):
                delta = np.where(old != 0, new / old, new).astype(dtype)
        elif op in (SnapshotMergeOperation.MAX, SnapshotMergeOperation.MIN):
            delta = new
        else:
            raise ValueError(f"Unsupported arithmetic op {op}")
        return SnapshotDiff(lo, np.ascontiguousarray(delta).tobytes(),
                            region.data_type, op)

    # ------------------------------------------------------------------
    # Applying / queueing
    # ------------------------------------------------------------------
    def apply_diff(self, diff: SnapshotDiff) -> None:
        with self._lock:
            lo = diff.offset
            hi = lo + len(diff.data)
            if hi > self._data.size:
                # Extension diffs (memory grown mid-batch) may exceed the
                # declared max; growth wins over a stale bound
                self.max_size = max(self.max_size, hi)
                self.resize(hi)
            op = diff.operation
            if op == SnapshotMergeOperation.BYTEWISE:
                self._data[lo:hi] = np.frombuffer(diff.data, np.uint8)
                return
            if op == SnapshotMergeOperation.XOR:
                self._data[lo:hi] = np.bitwise_xor(
                    self._data[lo:hi], np.frombuffer(diff.data, np.uint8))
                return
            dtype = _NP_TYPES[diff.data_type]
            target = self._data[lo:hi].view(dtype)
            value = np.frombuffer(diff.data, dtype)
            if op == SnapshotMergeOperation.SUM:
                target += value
            elif op == SnapshotMergeOperation.SUBTRACT:
                target -= value
            elif op == SnapshotMergeOperation.PRODUCT:
                np.multiply(target, value, out=target,
                            casting="unsafe")
            elif op == SnapshotMergeOperation.MAX:
                np.maximum(target, value, out=target)
            elif op == SnapshotMergeOperation.MIN:
                np.minimum(target, value, out=target)
            else:
                raise ValueError(f"Unsupported diff op {op}")

    def queue_diffs(self, diffs: Iterable[SnapshotDiff]) -> None:
        with self._lock:
            self._queued_diffs.extend(diffs)

    def queued_diff_count(self) -> int:
        with self._lock:
            return len(self._queued_diffs)

    def write_queued_diffs(self) -> int:
        """Apply (and drain) queued diffs; returns how many applied
        (reference writeQueuedDiffs)."""
        stats = get_state_stats()
        t0 = time.perf_counter() if stats.enabled else 0.0
        with self._lock:
            diffs = self._queued_diffs
            self._queued_diffs = []
        for d in diffs:
            self.apply_diff(d)
        if stats.enabled and diffs:
            stats.snapshot_event(
                "apply", nbytes=sum(len(d.data) for d in diffs),
                regions=len(diffs), seconds=time.perf_counter() - t0)
        return len(diffs)

    # ------------------------------------------------------------------
    def map_to_memory(self, mem) -> None:
        """Restore: copy the snapshot image into executor memory
        (reference mapToMemory — there MAP_PRIVATE; here a copy)."""
        stats = get_state_stats()
        t0 = time.perf_counter() if stats.enabled else 0.0
        dst = np.frombuffer(mem, dtype=np.uint8)
        if dst.size < self.size:
            raise ValueError(
                f"Target memory {dst.size} smaller than snapshot {self.size}")
        dst[:self.size] = self._data
        dst[self.size:] = 0
        if stats.enabled:
            stats.snapshot_event("restore", nbytes=self.size,
                                 seconds=time.perf_counter() - t0)


def _pages_to_ranges(flags: np.ndarray, limit: int) -> list[tuple[int, int]]:
    """Collapse page flags into contiguous byte ranges."""
    out: list[tuple[int, int]] = []
    run = None
    for i, dirty in enumerate(flags):
        if dirty and run is None:
            run = i
        elif not dirty and run is not None:
            out.append((run * PAGE_SIZE, min(i * PAGE_SIZE, limit)))
            run = None
    if run is not None:
        out.append((run * PAGE_SIZE, min(flags.size * PAGE_SIZE, limit)))
    return out
