"""Device-memory snapshots: dirty detection and diff extraction ON the chip.

SURVEY §7 names this hard part explicitly: there is no mprotect on HBM,
so fault-driven tracking (reference src/util/dirty.cpp) cannot exist for
device state. The TPU-native design: keep a baseline copy of the value
in HBM and let XLA do the page compare **on device** —

- ``dirty_pages(current)``: one compiled reduction producing an
  (n_pages,) bool vector; only those ~n/4096 bytes cross to the host.
- ``diff(current)``: gathers exactly the dirty pages on device (one
  ``take`` along the page axis) and transfers just them, emitting the
  same :class:`SnapshotDiff` objects the host snapshot stack ships over
  RPC (snapshot/remote.py) and merges (SnapshotData.queue_diffs).

A Pallas kernel would add nothing here: the compare is a pure
bandwidth-bound elementwise+reduce that XLA already fuses into a single
HBM pass; the win is architectural (never pulling the full image to the
host), not micro-kernel-level.

Byte-exactness: values are bitcast to a uint8 image on device, so page
offsets/bytes match the host-side SnapshotData layout exactly and a
device diff can be queued onto a host snapshot (checkpoint/freeze paths
ride the existing machinery).
"""

from __future__ import annotations

import functools

import numpy as np

from faabric_tpu.snapshot.snapshot import SnapshotData, SnapshotDiff

DEVICE_PAGE_SIZE = 4096


def _as_byte_image(arr):
    """Flatten any-dtype device array to its (nbytes,) uint8 image."""
    import jax
    import jax.numpy as jnp

    flat = arr.reshape(-1)
    if flat.dtype == jnp.uint8:
        return flat
    u8 = jax.lax.bitcast_convert_type(flat, jnp.uint8)
    return u8.reshape(-1)


@functools.lru_cache(maxsize=32)
def _flags_fn(n_bytes: int, page_size: int):
    import jax
    import jax.numpy as jnp

    n_pages = -(-n_bytes // page_size)
    pad = n_pages * page_size - n_bytes

    def flags(base_u8, cur_u8):
        b = jnp.pad(base_u8, (0, pad))
        c = jnp.pad(cur_u8, (0, pad))
        return jnp.any((b != c).reshape(n_pages, page_size), axis=1)

    return jax.jit(flags)


@functools.lru_cache(maxsize=32)
def _gather_fn(n_bytes: int, page_size: int):
    import jax
    import jax.numpy as jnp

    n_pages = -(-n_bytes // page_size)
    pad = n_pages * page_size - n_bytes

    def gather(cur_u8, idx):
        c = jnp.pad(cur_u8, (0, pad)).reshape(n_pages, page_size)
        return jnp.take(c, idx, axis=0)

    return jax.jit(gather)


def _bucket(n: int) -> int:
    """Round the dirty-page count up to a power of two so the gather
    compiles O(log) distinct shapes, not one per count."""
    b = 1
    while b < n:
        b <<= 1
    return b


class DeviceSnapshot:
    """Baseline-and-diff for one device-resident value.

    The baseline stays in HBM next to the live value (2× memory for the
    tracked array — the price of faultless tracking; jax.checkpoint-style
    rematerialization does not apply to opaque guest state). All compares
    and gathers are compiled once per (shape, page count) and cached.
    """

    def __init__(self, arr, page_size: int = DEVICE_PAGE_SIZE) -> None:
        import jax.numpy as jnp

        self.page_size = page_size
        self.shape = arr.shape
        self.dtype = arr.dtype
        self._baseline_u8 = jnp.copy(_as_byte_image(arr))
        self.n_bytes = int(self._baseline_u8.size)
        self.n_pages = -(-self.n_bytes // page_size)

    # ------------------------------------------------------------------
    def _flags_u8(self, u8) -> np.ndarray:
        return np.asarray(_flags_fn(self.n_bytes, self.page_size)(
            self._baseline_u8, u8))

    def dirty_pages(self, arr) -> np.ndarray:
        """(n_pages,) bool host vector; the only device→host transfer is
        the flag vector itself."""
        self._check(arr)
        return self._flags_u8(_as_byte_image(arr))

    def diff(self, arr, update_baseline: bool = False
             ) -> list[SnapshotDiff]:
        """Byte-exact diffs of ``arr`` vs the baseline; dirty pages are
        gathered on device and transferred in one batch. Adjacent dirty
        pages coalesce into a single diff."""
        self._check(arr)
        # One byte image serves the compare, the gather, and (optionally)
        # the baseline refresh — not one transient full-size copy each
        u8 = _as_byte_image(arr)
        idx = np.flatnonzero(self._flags_u8(u8))
        if idx.size == 0:
            return []
        # Pad the index list to a power-of-two bucket (repeating the last
        # page — harmlessly re-gathered, sliced off below) so distinct
        # dirty counts reuse O(log n) compiled gathers
        bucket = _bucket(idx.size)
        idx_padded = np.concatenate(
            [idx, np.full(bucket - idx.size, idx[-1], idx.dtype)])
        pages = np.asarray(_gather_fn(self.n_bytes, self.page_size)(
            u8, idx_padded))[:idx.size]
        diffs: list[SnapshotDiff] = []
        run_start = 0
        for i in range(1, idx.size + 1):
            if i == idx.size or idx[i] != idx[i - 1] + 1:
                first, last = idx[run_start], idx[i - 1]
                data = pages[run_start:i].reshape(-1)
                offset = int(first) * self.page_size
                # Clip the final page's padding back to the true size
                end = min((int(last) + 1) * self.page_size, self.n_bytes)
                diffs.append(SnapshotDiff(offset,
                                          data[:end - offset].tobytes()))
                run_start = i
        if update_baseline:
            import jax.numpy as jnp

            self._baseline_u8 = jnp.copy(u8)  # reuse the computed image
        return diffs

    def update_baseline(self, arr) -> None:
        import jax.numpy as jnp

        self._check(arr)
        self._baseline_u8 = jnp.copy(_as_byte_image(arr))

    def restore(self):
        """The baseline as a device array of the original shape/dtype."""
        import jax
        import jax.numpy as jnp

        flat = self._baseline_u8
        if self.dtype != jnp.uint8:
            itemsize = np.dtype(self.dtype).itemsize
            flat = jax.lax.bitcast_convert_type(
                flat.reshape(-1, itemsize), self.dtype)
        return flat.reshape(self.shape)

    # ------------------------------------------------------------------
    # Bridges to the host snapshot stack (freeze/thaw, RPC push)
    # ------------------------------------------------------------------
    def to_host_snapshot(self) -> SnapshotData:
        """The baseline as a host SnapshotData — device diffs queue onto
        it with the exact same byte offsets."""
        return SnapshotData(np.asarray(self._baseline_u8))

    def apply_diffs(self, arr, diffs: list[SnapshotDiff]):
        """Apply byte-exact diffs to a device value (the restore
        direction: thaw a frozen device state, then replay diffs)."""
        import jax
        import jax.numpy as jnp

        self._check(arr)
        u8 = np.asarray(_as_byte_image(arr)).copy()
        for d in diffs:
            u8[d.offset:d.offset + len(d.data)] = np.frombuffer(
                d.data, np.uint8)
        host = u8
        if self.dtype != jnp.uint8:
            host = host.view(self.dtype)
        return jax.device_put(host.reshape(self.shape))

    def _check(self, arr) -> None:
        if arr.shape != self.shape or arr.dtype != self.dtype:
            raise ValueError(
                f"Device snapshot tracks {self.shape}/{self.dtype}, got "
                f"{arr.shape}/{arr.dtype}")
