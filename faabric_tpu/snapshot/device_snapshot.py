"""Device-memory snapshots: dirty detection and diff extraction ON the chip.

SURVEY §7 names this hard part explicitly: there is no mprotect on HBM,
so fault-driven tracking (reference src/util/dirty.cpp) cannot exist for
device state. The TPU-native design: keep a baseline copy of the value
in HBM and let XLA do the page compare **on device** —

- ``dirty_pages(current)``: one compiled reduction producing an
  (n_pages,) bool vector; only those ~n/4096 bytes cross to the host.
- ``diff(current)``: gathers exactly the dirty pages on device (one
  ``take`` along the page axis) and transfers just them, emitting the
  same :class:`SnapshotDiff` objects the host snapshot stack ships over
  RPC (snapshot/remote.py) and merges (SnapshotData.queue_diffs).

A Pallas kernel would add nothing here: the compare is a pure
bandwidth-bound elementwise+reduce that XLA already fuses into a single
HBM pass; the win is architectural (never pulling the full image to the
host), not micro-kernel-level.

Compares and gathers run on a **same-width integer view** of the value
(bitcast, free on device), never on a uint8 byte image: a float32 page
is 1024 uint32 words vs 4096 bytes, and TPU vector units tile 32-bit
lanes natively, so the compare runs at HBM bandwidth instead of fighting
an int8 relayout. Byte-exactness is preserved — the bitcast keeps bit
patterns, so word equality is byte equality (unlike comparing floats,
where NaN != NaN and -0.0 == 0.0 would both lie about the bytes), and
diffs are emitted as the original little-endian byte ranges with offsets
matching the host-side SnapshotData layout exactly, so a device diff can
be queued onto a host snapshot (checkpoint/freeze paths ride the
existing machinery).
"""

from __future__ import annotations

import functools
import time

import numpy as np

from faabric_tpu.device_plane.copies import D2H, H2D, count_copy
from faabric_tpu.snapshot.snapshot import SnapshotData, SnapshotDiff
from faabric_tpu.telemetry.statestats import get_state_stats

DEVICE_PAGE_SIZE = 4096

_WORD_FOR_SIZE = {1: "uint8", 2: "uint16", 4: "uint32", 8: "uint64"}


def _word_dtype(dtype) -> np.dtype:
    """The unsigned-int dtype a value is compared as: same-width where
    one exists (the fast path), uint8 otherwise. Complex dtypes are
    rejected — XLA cannot bitcast them (view them as real pairs before
    tracking)."""
    dt = np.dtype(dtype)
    if dt.kind == "c":
        raise ValueError(
            f"DeviceSnapshot does not support complex dtype {dt}; "
            "bitcast/view the value as its real-pair components first")
    return np.dtype(_WORD_FOR_SIZE.get(dt.itemsize, "uint8"))


def _as_word_image(arr):
    """Flatten a (real-dtype) device array to its unsigned-int word
    image (a free bitcast — bit patterns, and therefore bytes, are
    preserved)."""
    import jax
    import jax.numpy as jnp

    flat = arr.reshape(-1)
    if flat.dtype == jnp.bool_:
        # No bitcast from bool; byte-equal for JAX's canonical 0/1 bools
        return flat.astype(jnp.uint8)
    word = _word_dtype(flat.dtype)
    if flat.dtype == word:
        return flat
    return jax.lax.bitcast_convert_type(flat, jnp.dtype(word)).reshape(-1)


@functools.lru_cache(maxsize=32)
def _flags_fn(n_words: int, page_words: int, word: str):
    import jax
    import jax.numpy as jnp

    n_pages = -(-n_words // page_words)
    pad = n_pages * page_words - n_words

    def flags(base_w, cur_w):
        b = jnp.pad(base_w, (0, pad))
        c = jnp.pad(cur_w, (0, pad))
        return jnp.any((b != c).reshape(n_pages, page_words), axis=1)

    return jax.jit(flags)


@functools.lru_cache(maxsize=32)
def _gather_fn(n_words: int, page_words: int, word: str):
    import jax
    import jax.numpy as jnp

    n_pages = -(-n_words // page_words)
    pad = n_pages * page_words - n_words

    def gather(cur_w, idx):
        c = jnp.pad(cur_w, (0, pad)).reshape(n_pages, page_words)
        return jnp.take(c, idx, axis=0)

    return jax.jit(gather)


def _bucket(n: int) -> int:
    """Round the dirty-page count up to a power of two so the gather
    compiles O(log) distinct shapes, not one per count."""
    b = 1
    while b < n:
        b <<= 1
    return b


class DeviceSnapshot:
    """Baseline-and-diff for one device-resident value.

    The baseline stays in HBM next to the live value (2× memory for the
    tracked array — the price of faultless tracking; jax.checkpoint-style
    rematerialization does not apply to opaque guest state). All compares
    and gathers are compiled once per (shape, page count) and cached.
    """

    def __init__(self, arr, page_size: int = DEVICE_PAGE_SIZE) -> None:
        import jax.numpy as jnp

        self.page_size = page_size
        self.shape = arr.shape
        self.dtype = arr.dtype
        self._baseline_w = jnp.copy(_as_word_image(arr))
        self._word = np.dtype(self._baseline_w.dtype)
        if page_size % self._word.itemsize:
            raise ValueError(
                f"page_size {page_size} not a multiple of item size "
                f"{self._word.itemsize}")
        self.page_words = page_size // self._word.itemsize
        self.n_words = int(self._baseline_w.size)
        self.n_bytes = self.n_words * self._word.itemsize
        self.n_pages = -(-self.n_words // self.page_words)

    # ------------------------------------------------------------------
    def _flags_w(self, w) -> np.ndarray:
        flags = np.asarray(_flags_fn(self.n_words, self.page_words,
                                     self._word.name)(self._baseline_w, w))
        # The architectural point of on-device diffing, made auditable
        # (ISSUE 15): the only device→host traffic of a compare is this
        # ~n/page_size flag vector, never the image
        count_copy(D2H, int(flags.nbytes), "snapshot")
        return flags

    def dirty_pages(self, arr) -> np.ndarray:
        """(n_pages,) bool host vector; the only device→host transfer is
        the flag vector itself."""
        self._check(arr)
        return self._flags_w(_as_word_image(arr))

    def diff(self, arr, update_baseline: bool = False
             ) -> list[SnapshotDiff]:
        """Byte-exact diffs of ``arr`` vs the baseline; dirty pages are
        gathered on device and transferred in one batch. Adjacent dirty
        pages coalesce into a single diff."""
        self._check(arr)
        stats = get_state_stats()
        t0 = time.perf_counter() if stats.enabled else 0.0
        # One word image serves the compare, the gather, and (optionally)
        # the baseline refresh — not one transient full-size copy each
        w = _as_word_image(arr)
        idx = np.flatnonzero(self._flags_w(w))
        if idx.size == 0:
            if stats.enabled:
                stats.snapshot_event("device_diff",
                                     seconds=time.perf_counter() - t0)
            return []
        # Pad the index list to a power-of-two bucket (repeating the last
        # page — harmlessly re-gathered, sliced off below) so distinct
        # dirty counts reuse O(log n) compiled gathers
        bucket = _bucket(idx.size)
        idx_padded = np.concatenate(
            [idx, np.full(bucket - idx.size, idx[-1], idx.dtype)])
        pages = np.asarray(_gather_fn(self.n_words, self.page_words,
                                      self._word.name)(w, idx_padded))
        count_copy(D2H, int(pages.nbytes), "snapshot")
        # (bucket, page_words) words → (bucket, page_size) bytes
        pages = pages[:idx.size].view(np.uint8).reshape(idx.size, -1)
        diffs: list[SnapshotDiff] = []
        run_start = 0
        for i in range(1, idx.size + 1):
            if i == idx.size or idx[i] != idx[i - 1] + 1:
                first, last = idx[run_start], idx[i - 1]
                data = pages[run_start:i].reshape(-1)
                offset = int(first) * self.page_size
                # Clip the final page's padding back to the true size
                end = min((int(last) + 1) * self.page_size, self.n_bytes)
                diffs.append(SnapshotDiff(offset,
                                          data[:end - offset].tobytes()))
                run_start = i
        if update_baseline:
            import jax.numpy as jnp

            self._baseline_w = jnp.copy(w)  # reuse the computed image
        if stats.enabled:
            stats.snapshot_event(
                "device_diff", nbytes=sum(len(d.data) for d in diffs),
                pages=int(idx.size), regions=len(diffs),
                seconds=time.perf_counter() - t0)
        return diffs

    @property
    def baseline_bytes(self) -> np.ndarray:
        """Host uint8 view of the baseline image (host bridging, tests)."""
        return np.asarray(self._baseline_w).view(np.uint8).reshape(-1)

    def update_baseline(self, arr) -> None:
        import jax.numpy as jnp

        self._check(arr)
        self._baseline_w = jnp.copy(_as_word_image(arr))

    def restore(self):
        """The baseline as a device array of the original shape/dtype."""
        import jax
        import jax.numpy as jnp

        flat = self._baseline_w
        if self.dtype == jnp.bool_:
            return (flat != 0).reshape(self.shape)
        if flat.dtype != self.dtype:
            ratio = (np.dtype(self.dtype).itemsize // self._word.itemsize)
            if ratio > 1:  # uint8-fallback words: group bytes per element
                flat = flat.reshape(-1, ratio)
            flat = jax.lax.bitcast_convert_type(flat, self.dtype)
            if flat.ndim > 1:
                flat = flat.reshape(-1)
        return flat.reshape(self.shape)

    # ------------------------------------------------------------------
    # Bridges to the host snapshot stack (freeze/thaw, RPC push)
    # ------------------------------------------------------------------
    def to_host_snapshot(self) -> SnapshotData:
        """The baseline as a host SnapshotData — device diffs queue onto
        it with the exact same byte offsets."""
        host = np.asarray(self._baseline_w)
        count_copy(D2H, int(host.nbytes), "snapshot")
        return SnapshotData(host.view(np.uint8))

    def apply_diffs(self, arr, diffs: list[SnapshotDiff]):
        """Apply byte-exact diffs to a device value (the restore
        direction: thaw a frozen device state, then replay diffs)."""
        import jax

        self._check(arr)
        host = np.asarray(arr)
        count_copy(D2H, int(host.nbytes), "snapshot")
        u8 = host.reshape(-1).view(np.uint8).copy()
        for d in diffs:
            u8[d.offset:d.offset + len(d.data)] = np.frombuffer(
                d.data, np.uint8)
        count_copy(H2D, int(u8.nbytes), "snapshot")
        return jax.device_put(u8.view(host.dtype).reshape(self.shape))

    def _check(self, arr) -> None:
        if arr.shape != self.shape or arr.dtype != self.dtype:
            raise ValueError(
                f"snapshot tracks {self.shape}/{self.dtype}, "
                f"got {arr.shape}/{arr.dtype}")
