"""Snapshot registry: key → SnapshotData per host (and on the planner for
THREADS/freeze distribution). Reference analog:
include/faabric/snapshot/SnapshotRegistry.h:13-44."""

from __future__ import annotations

import threading
from typing import Optional

from faabric_tpu.snapshot.snapshot import SnapshotData


class SnapshotRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._snapshots: dict[str, SnapshotData] = {}

    def register_snapshot(self, key: str, snap: SnapshotData) -> None:
        if not key:
            raise ValueError("Empty snapshot key")
        with self._lock:
            self._snapshots[key] = snap

    def get_snapshot(self, key: str) -> SnapshotData:
        with self._lock:
            snap = self._snapshots.get(key)
        if snap is None:
            raise KeyError(f"No snapshot registered for key {key}")
        return snap

    def try_get_snapshot(self, key: str) -> Optional[SnapshotData]:
        with self._lock:
            return self._snapshots.get(key)

    def snapshot_exists(self, key: str) -> bool:
        with self._lock:
            return key in self._snapshots

    def delete_snapshot(self, key: str) -> None:
        with self._lock:
            self._snapshots.pop(key, None)

    def get_snapshot_count(self) -> int:
        with self._lock:
            return len(self._snapshots)

    def clear(self) -> None:
        with self._lock:
            self._snapshots.clear()
