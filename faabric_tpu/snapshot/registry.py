"""Snapshot registry: key → SnapshotData per host (and on the planner for
THREADS/freeze distribution). Reference analog:
include/faabric/snapshot/SnapshotRegistry.h:13-44."""

from __future__ import annotations

import threading
from typing import Optional

from faabric_tpu.snapshot.snapshot import SnapshotData
from faabric_tpu.telemetry.statestats import get_state_stats


class SnapshotRegistry:
    # Concurrency contract (tools/concheck.py)
    GUARDS = {
        "_snapshots": "_lock",
    }

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._snapshots: dict[str, SnapshotData] = {}

    def register_snapshot(self, key: str, snap: SnapshotData) -> None:
        if not key:
            raise ValueError("Empty snapshot key")
        with self._lock:
            self._snapshots[key] = snap
        self._note_residency()

    def get_snapshot(self, key: str) -> SnapshotData:
        with self._lock:
            snap = self._snapshots.get(key)
        if snap is None:
            raise KeyError(f"No snapshot registered for key {key}")
        return snap

    def try_get_snapshot(self, key: str) -> Optional[SnapshotData]:
        with self._lock:
            return self._snapshots.get(key)

    def snapshot_exists(self, key: str) -> bool:
        with self._lock:
            return key in self._snapshots

    def delete_snapshot(self, key: str) -> None:
        with self._lock:
            self._snapshots.pop(key, None)
        self._note_residency()

    def get_snapshot_count(self) -> int:
        with self._lock:
            return len(self._snapshots)

    def resident_bytes(self) -> int:
        """Total bytes of registered snapshot images on this host."""
        with self._lock:
            snaps = list(self._snapshots.values())
        return sum(s.size for s in snaps)

    def clear(self) -> None:
        with self._lock:
            self._snapshots.clear()
        self._note_residency()

    def _note_residency(self) -> None:
        stats = get_state_stats()
        if stats.enabled:
            stats.set_registry_bytes(self.resident_bytes())
