"""Snapshot RPC: server (ports 8007/8008) + client with mock recording.

Reference analog: src/snapshot/SnapshotServer.cpp:64-105 and
src/snapshot/SnapshotClient.cpp (281 lines), flatbuffer schema
src/flat/faabric.fbs. Contents and diff bytes ride the transport frame's
binary tail (the zero-copy analog); merge-region/diff metadata travels in
the JSON header.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import TYPE_CHECKING

from faabric_tpu.snapshot.snapshot import (
    MergeRegion,
    SnapshotData,
    SnapshotDataType,
    SnapshotDiff,
    SnapshotMergeOperation,
)
from faabric_tpu.telemetry import span
from faabric_tpu.telemetry.statestats import get_state_stats
from faabric_tpu.transport.client import MessageEndpointClient
from faabric_tpu.transport.common import (
    SNAPSHOT_ASYNC_PORT,
    SNAPSHOT_SYNC_PORT,
    get_host_alias_offset,
)
from faabric_tpu.transport.message import TransportMessage
from faabric_tpu.transport.server import MessageEndpointServer, handler_response
from faabric_tpu.util.config import get_system_config
from faabric_tpu.util.logging import get_logger
from faabric_tpu.util.testing import is_mock_mode

if TYPE_CHECKING:  # pragma: no cover
    from faabric_tpu.snapshot.registry import SnapshotRegistry

logger = get_logger(__name__)


class SnapshotCalls(enum.IntEnum):
    PUSH_SNAPSHOT = 1
    PUSH_SNAPSHOT_UPDATE = 2
    THREAD_RESULT = 3
    DELETE_SNAPSHOT = 4


# ---------------------------------------------------------------------------
# Mock recording (reference SnapshotClient mocks)
# ---------------------------------------------------------------------------
_mock_lock = threading.Lock()
_pushes: list[tuple[str, str, "SnapshotData"]] = []
_diff_pushes: list[tuple[str, str, list[SnapshotDiff]]] = []
_thread_results: list[tuple[str, int, int, int]] = []


def get_snapshot_pushes() -> list[tuple[str, str, "SnapshotData"]]:
    with _mock_lock:
        return list(_pushes)


def get_snapshot_diff_pushes() -> list[tuple[str, str, list[SnapshotDiff]]]:
    with _mock_lock:
        return list(_diff_pushes)


def get_mock_thread_results() -> list[tuple[str, int, int, int]]:
    with _mock_lock:
        return list(_thread_results)


def clear_mock_snapshot_requests() -> None:
    with _mock_lock:
        _pushes.clear()
        _diff_pushes.clear()
        _thread_results.clear()


# ---------------------------------------------------------------------------
# Wire helpers: diff metadata in header, bytes concatenated in the tail
# ---------------------------------------------------------------------------

def diffs_to_wire(diffs: list[SnapshotDiff]) -> tuple[list[dict], bytes]:
    tail = bytearray()
    metas = []
    for d in diffs:
        metas.append(d.to_dict())
        tail += d.data
    return metas, bytes(tail)


def diffs_from_wire(metas: list[dict], tail: bytes) -> list[SnapshotDiff]:
    out = []
    off = 0
    for m in metas:
        length = int(m["length"])
        out.append(SnapshotDiff(
            offset=int(m["offset"]),
            data=tail[off:off + length],
            data_type=SnapshotDataType(m.get("data_type", 0)),
            operation=SnapshotMergeOperation(m.get("operation", 0)),
        ))
        off += length
    return out


class SnapshotClient(MessageEndpointClient):
    def __init__(self, host: str) -> None:
        super().__init__(host, SNAPSHOT_ASYNC_PORT, SNAPSHOT_SYNC_PORT)

    def push_snapshot(self, key: str, snap: SnapshotData) -> None:
        if is_mock_mode():
            with _mock_lock:
                _pushes.append((self.host, key, snap))
            return
        header = {
            "key": key,
            "max_size": snap.max_size,
            "merge_regions": [r.to_dict() for r in snap.get_merge_regions()],
        }
        from faabric_tpu.util.bytes import format_byte_size

        logger.debug("Pushing snapshot %s (%s) to %s", key,
                     format_byte_size(snap.size), self.host)
        stats = get_state_stats()
        t0 = time.perf_counter() if stats.enabled else 0.0
        with span("snapshot", "push", key=key, nbytes=snap.size):
            self.sync_send(int(SnapshotCalls.PUSH_SNAPSHOT), header,
                           snap.to_bytes())
        if stats.enabled:
            stats.snapshot_event("push", nbytes=snap.size,
                                 seconds=time.perf_counter() - t0)

    def push_snapshot_update(self, key: str,
                             diffs: list[SnapshotDiff]) -> None:
        if is_mock_mode():
            with _mock_lock:
                _diff_pushes.append((self.host, key, diffs))
            return
        metas, tail = diffs_to_wire(diffs)
        stats = get_state_stats()
        t0 = time.perf_counter() if stats.enabled else 0.0
        with span("snapshot", "push_update", key=key, nbytes=len(tail)):
            self.sync_send(int(SnapshotCalls.PUSH_SNAPSHOT_UPDATE),
                           {"key": key, "diffs": metas}, tail)
        if stats.enabled:
            stats.snapshot_event("push", nbytes=len(tail),
                                 regions=len(diffs),
                                 seconds=time.perf_counter() - t0)

    def push_thread_result(self, app_id: int, msg_id: int, return_value: int,
                           key: str, diffs: list[SnapshotDiff]) -> None:
        """Remote THREADS result: return value + this thread's diffs,
        queued on the main host's snapshot (reference pushThreadResult)."""
        if is_mock_mode():
            with _mock_lock:
                _thread_results.append((self.host, app_id, msg_id,
                                        return_value))
                _diff_pushes.append((self.host, key, diffs))
            return
        metas, tail = diffs_to_wire(diffs)
        stats = get_state_stats()
        t0 = time.perf_counter() if stats.enabled else 0.0
        with span("snapshot", "thread_result", key=key, nbytes=len(tail)):
            self.sync_send(int(SnapshotCalls.THREAD_RESULT), {
                "app_id": app_id, "msg_id": msg_id,
                "return_value": return_value, "key": key, "diffs": metas,
            }, tail)
        if stats.enabled:
            stats.snapshot_event("push", nbytes=len(tail),
                                 regions=len(diffs),
                                 seconds=time.perf_counter() - t0)

    def delete_snapshot(self, key: str) -> None:
        if is_mock_mode():
            return
        self.async_send(int(SnapshotCalls.DELETE_SNAPSHOT), {"key": key})


class SnapshotServer(MessageEndpointServer):
    def __init__(self, registry: "SnapshotRegistry", host: str = "",
                 scheduler=None, port_offset: int | None = None) -> None:
        conf = get_system_config()
        offset = port_offset if port_offset is not None \
            else get_host_alias_offset(host)
        super().__init__(
            SNAPSHOT_ASYNC_PORT + offset,
            SNAPSHOT_SYNC_PORT + offset,
            label=f"snapshot-server-{host or 'local'}",
            n_threads=conf.snapshot_server_threads,
        )
        self.registry = registry
        self.scheduler = scheduler  # for thread-result delivery

    def do_async_recv(self, msg: TransportMessage) -> None:
        if msg.code == int(SnapshotCalls.DELETE_SNAPSHOT):
            self.registry.delete_snapshot(msg.header["key"])
        else:
            logger.warning("Unknown async snapshot call %d", msg.code)

    def do_sync_recv(self, msg: TransportMessage) -> TransportMessage:
        code = msg.code
        h = msg.header

        if code == int(SnapshotCalls.PUSH_SNAPSHOT):
            snap = SnapshotData(msg.payload, max_size=h.get("max_size", 0))
            for r in h.get("merge_regions", []):
                region = MergeRegion.from_dict(r)
                snap.add_merge_region(region.offset, region.length,
                                      region.data_type, region.operation)
            self.registry.register_snapshot(h["key"], snap)
            return handler_response()

        if code == int(SnapshotCalls.PUSH_SNAPSHOT_UPDATE):
            snap = self.registry.get_snapshot(h["key"])
            diffs = diffs_from_wire(h.get("diffs", []), msg.payload)
            snap.queue_diffs(diffs)
            return handler_response(header={"queued": len(diffs)})

        if code == int(SnapshotCalls.THREAD_RESULT):
            # Result delivery must never be gated on the snapshot lookup:
            # a missing/empty key drops the diffs but still wakes waiters
            key = h.get("key", "")
            snap = self.registry.try_get_snapshot(key) if key else None
            diffs = diffs_from_wire(h.get("diffs", []), msg.payload)
            if snap is not None:
                snap.queue_diffs(diffs)
            elif diffs:
                logger.warning(
                    "Dropping %d thread diffs for unknown snapshot %r",
                    len(diffs), key)
            if self.scheduler is not None:
                from faabric_tpu.proto import Message

                result = Message(id=h["msg_id"], app_id=h["app_id"],
                                 return_value=h["return_value"])
                self.scheduler.set_thread_result_locally(
                    result, h["return_value"])
            return handler_response()

        raise ValueError(f"Unknown sync snapshot call {code}")
