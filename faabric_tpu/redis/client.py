"""Pure-Python RESP2 Redis client.

Reference analog: the hiredis wrapper include/faabric/redis/Redis.h:81-228
and src/redis/Redis.cpp — per-role, per-thread client instances
(``Redis::getState()``/``getQueue()``), KV/range/set/list ops, pipelined
range writes, blocking dequeue. This image ships no redis client library,
so the client speaks the wire protocol directly (RESP2 is ~200 lines);
it works against a real Redis server or the in-repo
:mod:`faabric_tpu.redis.miniserver`.
"""

from __future__ import annotations

import socket
import threading
from typing import Optional


class RedisError(RuntimeError):
    """Server-side error reply (RESP '-' line)."""


class RedisConnectionError(ConnectionError):
    pass


def _encode_command(*args) -> bytes:
    """RESP array of bulk strings; str/int args are utf-8 encoded."""
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        if isinstance(a, bytes):
            b = a
        elif isinstance(a, memoryview):
            b = bytes(a)
        else:
            b = str(a).encode()
        out.append(b"$%d\r\n" % len(b))
        out.append(b)
        out.append(b"\r\n")
    return b"".join(out)


class RedisClient:
    """One TCP connection; NOT thread-safe — use :func:`get_redis` for a
    per-thread instance (the reference keeps per-thread hiredis contexts
    for the same reason)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0) -> None:
        self.host = host
        self.port = port
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._buf = bytearray()

    # -- connection ----------------------------------------------------
    def _connect(self) -> socket.socket:
        if self._sock is None:
            try:
                from faabric_tpu.util.network import safe_create_connection

                s = safe_create_connection((self.host, self.port),
                                           timeout=self.timeout)
            except OSError as e:
                raise RedisConnectionError(
                    f"Cannot reach redis at {self.host}:{self.port}: {e}"
                ) from e
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
            self._buf = bytearray()
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
                self._buf = bytearray()

    # -- RESP parsing (bytearray accumulation + recv_into for bulk
    # payloads — naive `bytes += chunk` would be O(n^2) on the
    # multi-hundred-MiB state pulls this backend exists for) -----------
    def _read_line(self) -> bytes:
        sock = self._connect()
        buf = self._buf
        while True:
            idx = buf.find(b"\r\n")
            if idx >= 0:
                line = bytes(buf[:idx])
                del buf[:idx + 2]
                return line
            chunk = sock.recv(65536)
            if not chunk:
                self.close()
                raise RedisConnectionError("redis connection closed")
            buf.extend(chunk)

    def _read_exact(self, n: int) -> bytes:
        buf = self._buf
        if len(buf) >= n:
            out = bytes(buf[:n])
            del buf[:n]
            return out
        sock = self._connect()
        out = bytearray(n)
        got = len(buf)
        out[:got] = buf
        buf.clear()
        mv = memoryview(out)
        while got < n:
            k = sock.recv_into(mv[got:])
            if not k:
                self.close()
                raise RedisConnectionError("redis connection closed")
            got += k
        return bytes(out)

    def _read_reply(self):
        reply = self._read_reply_any()
        if isinstance(reply, RedisError):
            raise reply
        return reply

    def _read_reply_any(self):
        """Parse one reply, returning errors as RedisError VALUES — the
        whole reply (including every element of an array that embeds an
        error) is always consumed, so the stream stays in sync; only the
        top level raises."""
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest
        if kind == b"-":
            return RedisError(rest.decode(errors="replace"))
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n < 0:
                return None
            data = self._read_exact(n)
            self._read_exact(2)  # trailing \r\n
            return data
        if kind == b"*":
            n = int(rest)
            if n < 0:
                return None
            items = [self._read_reply_any() for _ in range(n)]
            for it in items:
                if isinstance(it, RedisError):
                    return it  # array fully drained; surface the error
            return items
        self.close()  # unparseable stream — cannot stay in sync
        raise RedisError(f"Bad RESP type byte {kind!r}")

    # -- command execution ---------------------------------------------
    # Any socket-level failure mid-exchange (send fails, recv times out)
    # leaves the reply stream desynced — a late reply would be consumed
    # as the answer to the NEXT command, silently corrupting reads. Drop
    # the connection on those; a server '-ERR' reply is a complete,
    # in-sync reply and keeps the connection.
    def execute(self, *args):
        try:
            self._connect().sendall(_encode_command(*args))
            return self._read_reply()
        except (OSError, RedisConnectionError):
            self.close()
            raise

    def pipeline(self, commands: list[tuple]) -> list:
        """Send N commands in one write, read N replies (the reference
        pipelines its setRange writes, Redis.cpp setRangePipeline). All
        N replies are drained even when some are errors — the stream
        stays in sync — then the first error is raised."""
        if not commands:
            return []
        payload = b"".join(_encode_command(*c) for c in commands)
        replies: list = []
        try:
            self._connect().sendall(payload)
            for _ in commands:
                replies.append(self._read_reply_any())
        except (OSError, RedisConnectionError):
            self.close()
            raise
        for r in replies:
            if isinstance(r, RedisError):
                raise r
        return replies

    # -- string / KV ----------------------------------------------------
    def ping(self) -> bool:
        return self.execute("PING") == b"PONG"

    def get(self, key) -> Optional[bytes]:
        return self.execute("GET", key)

    def set(self, key, value) -> None:
        self.execute("SET", key, value)

    def setnx(self, key, value) -> bool:
        return bool(self.execute("SETNX", key, value))

    def set_nx_px(self, key, value, px_ms: int) -> bool:
        return self.execute("SET", key, value, "NX", "PX", px_ms) is not None

    def getrange(self, key, start: int, end: int) -> bytes:
        return self.execute("GETRANGE", key, start, end) or b""

    def setrange(self, key, offset: int, value) -> int:
        return self.execute("SETRANGE", key, offset, value)

    def setrange_pipeline(self, key, writes: list[tuple[int, bytes]]) -> None:
        self.pipeline([("SETRANGE", key, off, data) for off, data in writes])

    def strlen(self, key) -> int:
        return self.execute("STRLEN", key)

    def append(self, key, value) -> int:
        return self.execute("APPEND", key, value)

    def delete(self, *keys) -> int:
        return self.execute("DEL", *keys)

    def exists(self, key) -> bool:
        return bool(self.execute("EXISTS", key))

    def expire(self, key, seconds: int) -> bool:
        return bool(self.execute("EXPIRE", key, seconds))

    def incr(self, key) -> int:
        return self.execute("INCR", key)

    def decr(self, key) -> int:
        return self.execute("DECR", key)

    def incrby(self, key, n: int) -> int:
        return self.execute("INCRBY", key, n)

    def keys(self, pattern: str = "*") -> list[bytes]:
        return self.execute("KEYS", pattern) or []

    def flushall(self) -> None:
        self.execute("FLUSHALL")

    # -- sets (reference: master registry / scheduler sets) --------------
    def sadd(self, key, *members) -> int:
        return self.execute("SADD", key, *members)

    def srem(self, key, *members) -> int:
        return self.execute("SREM", key, *members)

    def smembers(self, key) -> set[bytes]:
        return set(self.execute("SMEMBERS", key) or [])

    def sismember(self, key, member) -> bool:
        return bool(self.execute("SISMEMBER", key, member))

    def scard(self, key) -> int:
        return self.execute("SCARD", key)

    def srandmember(self, key) -> Optional[bytes]:
        return self.execute("SRANDMEMBER", key)

    # -- lists (reference: queue role, result queues, appends) ----------
    def rpush(self, key, *values) -> int:
        return self.execute("RPUSH", key, *values)

    def lpush(self, key, *values) -> int:
        return self.execute("LPUSH", key, *values)

    def lpop(self, key) -> Optional[bytes]:
        return self.execute("LPOP", key)

    def rpop(self, key) -> Optional[bytes]:
        return self.execute("RPOP", key)

    def llen(self, key) -> int:
        return self.execute("LLEN", key)

    def lrange(self, key, start: int, stop: int) -> list[bytes]:
        return self.execute("LRANGE", key, start, stop) or []

    def blpop(self, key, timeout_s: float = 0) -> Optional[bytes]:
        """Blocking dequeue (reference dequeueBytes). Returns the value
        (without the key echo), or None on timeout. ``timeout_s=0`` means
        block forever (Redis semantics) — the socket timeout is lifted
        for the call so the client blocks with the server."""
        prev = self.timeout
        # The socket must outlast the server-side block
        self.timeout = (timeout_s + 5.0) if timeout_s else None
        if self._sock is not None:
            self._sock.settimeout(self.timeout)
        try:
            reply = self.execute("BLPOP", key, timeout_s)
        finally:
            self.timeout = prev
            if self._sock is not None:
                self._sock.settimeout(prev)
        if reply is None:
            return None
        return reply[1]

    # -- compare-and-delete (reference delifeq Lua script) --------------
    DELIFEQ_LUA = ("if redis.call('get', KEYS[1]) == ARGV[1] then "
                   "return redis.call('del', KEYS[1]) else return 0 end")

    def del_if_eq(self, key, expected) -> bool:
        """Atomically delete ``key`` iff its value equals ``expected`` —
        the reference's delifeq Lua script (Redis.h delifeqSha), sent via
        EVAL so a real Redis runs it server-side; the miniserver
        recognizes this exact script and applies it under its command
        lock. Atomicity matters across lock-TTL expiry: a GET+DEL pair
        could delete a NEW holder's token that slipped in between."""
        return bool(self.execute("EVAL", self.DELIFEQ_LUA, 1, key, expected))


_tls = threading.local()


def get_redis(role: str = "state") -> RedisClient:
    """Per-thread, per-role client (reference Redis::getState/getQueue)."""
    from faabric_tpu.util.config import get_system_config

    conf = get_system_config()
    host = (conf.redis_state_host if role == "state"
            else conf.redis_queue_host)
    port = conf.redis_port
    cache = getattr(_tls, "clients", None)
    if cache is None:
        cache = _tls.clients = {}
    cli = cache.get((role, host, port))
    if cli is None:
        cli = cache[(role, host, port)] = RedisClient(host, port)
    return cli


def clear_thread_clients() -> None:
    cache = getattr(_tls, "clients", None)
    if cache:
        for cli in cache.values():
            cli.close()
        cache.clear()
