"""Redis layer: pure-Python RESP2 client + in-repo mini server.

Reference analog: src/redis/Redis.cpp (hiredis wrapper) and the
dockerised redis service its deployments assume. Here the client speaks
RESP2 directly (no client lib in the image) and the mini server makes
``STATE_MODE=redis`` self-contained for tests/single-host runs.
"""

from faabric_tpu.redis.client import (
    RedisClient,
    RedisConnectionError,
    RedisError,
    clear_thread_clients,
    get_redis,
)
from faabric_tpu.redis.miniserver import MiniRedisServer

__all__ = [
    "RedisClient",
    "RedisConnectionError",
    "RedisError",
    "MiniRedisServer",
    "clear_thread_clients",
    "get_redis",
]
