"""Minimal RESP2 server — an in-process Redis stand-in.

Implements the command subset the framework's Redis wrapper uses
(strings/ranges, counters, expiry, sets, lists with BLPOP), RESP2 wire
format, one thread per connection, one global store lock per command
(real Redis is single-threaded per command — same atomicity model).

Purpose: the image ships no Redis server, but ``STATE_MODE=redis`` must
be a real, testable mode, not an interface slot — tests and single-host
deployments run against this; production points the same client at a
real Redis. Reference analog: the dockerised `redis` service every
faabric deployment assumes (docker-compose.yml) and the op surface of
include/faabric/redis/Redis.h:81-228.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Optional


def _now() -> float:
    return time.monotonic()


class _Store:
    """Keyspace with passive expiry. Values: bytes (string), set, list."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.data: dict[bytes, object] = {}
        self.expiry: dict[bytes, float] = {}
        # Signalled on every list push so BLPOP waiters re-check
        self.push_cond = threading.Condition(self.lock)

    def _expired(self, key: bytes) -> bool:
        exp = self.expiry.get(key)
        if exp is not None and _now() >= exp:
            self.data.pop(key, None)
            self.expiry.pop(key, None)
            return True
        return False

    def get(self, key: bytes):
        if self._expired(key):
            return None
        return self.data.get(key)

    def set(self, key: bytes, value) -> None:
        self.data[key] = value
        self.expiry.pop(key, None)


class MiniRedisServer:
    """``start()`` binds and serves on a background thread;
    ``stop()`` tears down the listener and live connections."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.host = host
        self.port = port
        self.store = _Store()
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        # Live connections only — entries are pruned as handlers exit,
        # so a long-running service doesn't grow per connection accepted
        self._conns_lock = threading.Lock()
        self._conns: dict[socket.socket, threading.Thread] = {}
        self._stop = threading.Event()

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        s.listen(64)
        self.port = s.getsockname()[1]
        self._listener = s
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="redis/accept", daemon=True)
        self._accept_thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        # Wake any BLPOP waiters so their threads observe _stop
        with self.store.push_cond:
            self.store.push_cond.notify_all()
        with self._conns_lock:
            live = list(self._conns.items())
        for c, _ in live:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        for _, t in live:
            t.join(timeout=5.0)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="redis/conn", daemon=True)
            with self._conns_lock:
                self._conns[conn] = t
            t.start()

    # -- RESP parsing ---------------------------------------------------
    def _serve_conn(self, conn: socket.socket) -> None:
        # bytearray accumulation + recv_into for bulk payloads (bytes +=
        # would be O(n^2) on multi-MiB SETRANGE bodies)
        buf = bytearray()

        def read_more() -> bool:
            try:
                chunk = conn.recv(65536)
            except OSError:
                return False
            if not chunk:
                return False
            buf.extend(chunk)
            return True

        def read_line() -> Optional[bytes]:
            while True:
                idx = buf.find(b"\r\n")
                if idx >= 0:
                    line = bytes(buf[:idx])
                    del buf[:idx + 2]
                    return line
                if not read_more():
                    return None

        def read_exact(n: int) -> Optional[bytes]:
            if len(buf) >= n:
                out = bytes(buf[:n])
                del buf[:n]
                return out
            out = bytearray(n)
            got = len(buf)
            out[:got] = buf
            buf.clear()
            mv = memoryview(out)
            while got < n:
                try:
                    k = conn.recv_into(mv[got:])
                except OSError:
                    return None
                if not k:
                    return None
                got += k
            return bytes(out)

        try:
            while not self._stop.is_set():
                line = read_line()
                if line is None:
                    return
                if not line.startswith(b"*"):
                    conn.sendall(b"-ERR protocol: expected array\r\n")
                    return
                try:
                    n_args = int(line[1:])
                except ValueError:
                    conn.sendall(b"-ERR protocol: bad array length\r\n")
                    return
                if n_args <= 0 or n_args > 1024 * 1024:
                    conn.sendall(b"-ERR protocol: bad arity\r\n")
                    return
                args: list[bytes] = []
                ok = True
                for _ in range(n_args):
                    hdr = read_line()
                    if hdr is None or not hdr.startswith(b"$"):
                        ok = False
                        break
                    try:
                        ln = int(hdr[1:])
                    except ValueError:
                        ok = False
                        break
                    # Bulk length is client-supplied and read_exact
                    # preallocates it — cap before a bogus $1099511627776
                    # header turns into a TiB allocation
                    if ln < 0 or ln > 1 << 30:
                        conn.sendall(b"-ERR protocol: bulk too large\r\n")
                        return
                    body = read_exact(ln)
                    if body is None or read_exact(2) is None:
                        ok = False
                        break
                    args.append(body)
                if not ok:
                    return
                try:
                    reply = self._dispatch(args)
                except _Error as e:
                    reply = b"-ERR " + str(e).encode() + b"\r\n"
                except Exception as e:  # noqa: BLE001 — contain per-command
                    reply = b"-ERR internal: " + repr(e).encode()[:120] \
                        + b"\r\n"
                try:
                    conn.sendall(reply)
                except OSError:
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._conns_lock:
                self._conns.pop(conn, None)

    # -- encoding helpers ----------------------------------------------
    @staticmethod
    def _bulk(v: Optional[bytes]) -> bytes:
        if v is None:
            return b"$-1\r\n"
        return b"$%d\r\n%s\r\n" % (len(v), v)

    @staticmethod
    def _int(n: int) -> bytes:
        return b":%d\r\n" % n

    @staticmethod
    def _arr(items: list[Optional[bytes]]) -> bytes:
        return b"*%d\r\n" % len(items) + b"".join(
            MiniRedisServer._bulk(i) for i in items)

    # -- command dispatch ----------------------------------------------
    def _dispatch(self, args: list[bytes]) -> bytes:
        cmd = args[0].upper().decode(errors="replace")
        handler = getattr(self, "_cmd_" + cmd.lower(), None)
        if handler is None:
            raise _Error(f"unknown command '{cmd}'")
        st = self.store
        if cmd == "BLPOP":  # manages the lock itself (waits on the cond)
            return handler(args[1:])
        with st.lock:
            return handler(args[1:])

    # All handlers run under the store lock (except BLPOP).
    def _cmd_ping(self, a):
        return b"+PONG\r\n"

    def _cmd_eval(self, a):
        """Only the framework's delifeq script (client.DELIFEQ_LUA) —
        recognized by source text and applied atomically under the
        command lock, matching what a real Redis does server-side."""
        from faabric_tpu.redis.client import RedisClient

        script = a[0].decode(errors="replace")
        if script != RedisClient.DELIFEQ_LUA or int(a[1]) != 1:
            raise _Error("unsupported EVAL script (miniserver runs only "
                         "the framework's delifeq)")
        key, expected = a[2], a[3]
        v = self._get_str(key)
        if v is not None and bytes(v) == expected:
            self.store.data.pop(key, None)
            self.store.expiry.pop(key, None)
            return self._int(1)
        return self._int(0)

    def _cmd_flushall(self, a):
        self.store.data.clear()
        self.store.expiry.clear()
        return b"+OK\r\n"

    def _get_str(self, key: bytes) -> Optional[bytearray]:
        v = self.store.get(key)
        if v is None:
            return None
        if not isinstance(v, bytearray):
            raise _Error("WRONGTYPE not a string")
        return v

    def _cmd_get(self, a):
        v = self._get_str(a[0])
        return self._bulk(bytes(v) if v is not None else None)

    def _cmd_set(self, a):
        key, value, rest = a[0], a[1], [x.upper() for x in a[2:]]
        nx = b"NX" in rest
        px_ms = None
        if b"PX" in rest:
            px_ms = int(rest[rest.index(b"PX") + 1])
        if nx and self.store.get(key) is not None:
            return self._bulk(None)
        self.store.set(key, bytearray(value))
        if px_ms is not None:
            self.store.expiry[key] = _now() + px_ms / 1000.0
        return b"+OK\r\n"

    def _cmd_setnx(self, a):
        if self.store.get(a[0]) is not None:
            return self._int(0)
        self.store.set(a[0], bytearray(a[1]))
        return self._int(1)

    def _cmd_strlen(self, a):
        v = self._get_str(a[0])
        return self._int(len(v) if v is not None else 0)

    def _cmd_append(self, a):
        v = self._get_str(a[0])
        if v is None:
            v = bytearray()
            self.store.set(a[0], v)
        v.extend(a[1])
        return self._int(len(v))

    def _cmd_getrange(self, a):
        v = self._get_str(a[0]) or bytearray()
        start, end = int(a[1]), int(a[2])
        n = len(v)
        if start < 0:
            start += n
        if end < 0:
            end += n
        return self._bulk(bytes(v[max(0, start):end + 1]))

    def _cmd_setrange(self, a):
        key, off, data = a[0], int(a[1]), a[2]
        v = self._get_str(key)
        if v is None:
            v = bytearray()
            self.store.set(key, v)
        if len(v) < off + len(data):
            v.extend(b"\x00" * (off + len(data) - len(v)))
        v[off:off + len(data)] = data
        return self._int(len(v))

    def _cmd_del(self, a):
        n = 0
        for key in a:
            if self.store.data.pop(key, None) is not None:
                n += 1
            self.store.expiry.pop(key, None)
        return self._int(n)

    def _cmd_exists(self, a):
        return self._int(sum(1 for k in a if self.store.get(k) is not None))

    def _cmd_expire(self, a):
        if self.store.get(a[0]) is None:
            return self._int(0)
        self.store.expiry[a[0]] = _now() + int(a[1])
        return self._int(1)

    def _counter(self, key: bytes, delta: int) -> bytes:
        v = self._get_str(key)
        cur = int(bytes(v)) if v else 0
        cur += delta
        self.store.set(key, bytearray(str(cur).encode()))
        return self._int(cur)

    def _cmd_incr(self, a):
        return self._counter(a[0], 1)

    def _cmd_decr(self, a):
        return self._counter(a[0], -1)

    def _cmd_incrby(self, a):
        return self._counter(a[0], int(a[1]))

    def _cmd_keys(self, a):
        import fnmatch

        pat = a[0].decode(errors="replace")
        live = [k for k in list(self.store.data)
                if not self.store._expired(k)]
        return self._arr(sorted(
            k for k in live if fnmatch.fnmatchcase(
                k.decode(errors="replace"), pat)))

    # -- sets -----------------------------------------------------------
    def _get_set(self, key: bytes) -> Optional[set]:
        v = self.store.get(key)
        if v is None:
            return None
        if not isinstance(v, set):
            raise _Error("WRONGTYPE not a set")
        return v

    def _cmd_sadd(self, a):
        s = self._get_set(a[0])
        if s is None:
            s = set()
            self.store.set(a[0], s)
        n = 0
        for m in a[1:]:
            if m not in s:
                s.add(bytes(m))
                n += 1
        return self._int(n)

    def _cmd_srem(self, a):
        s = self._get_set(a[0]) or set()
        n = 0
        for m in a[1:]:
            if m in s:
                s.discard(m)
                n += 1
        return self._int(n)

    def _cmd_smembers(self, a):
        return self._arr(sorted(self._get_set(a[0]) or set()))

    def _cmd_sismember(self, a):
        return self._int(int(a[1] in (self._get_set(a[0]) or set())))

    def _cmd_scard(self, a):
        return self._int(len(self._get_set(a[0]) or set()))

    def _cmd_srandmember(self, a):
        s = self._get_set(a[0])
        return self._bulk(next(iter(s)) if s else None)

    # -- lists ----------------------------------------------------------
    def _get_list(self, key: bytes) -> Optional[list]:
        v = self.store.get(key)
        if v is None:
            return None
        if not isinstance(v, list):
            raise _Error("WRONGTYPE not a list")
        return v

    def _push(self, key: bytes, values: list[bytes], left: bool) -> bytes:
        lst = self._get_list(key)
        if lst is None:
            lst = []
            self.store.set(key, lst)
        for v in values:
            if left:
                lst.insert(0, bytes(v))
            else:
                lst.append(bytes(v))
        self.store.push_cond.notify_all()
        return self._int(len(lst))

    def _cmd_rpush(self, a):
        return self._push(a[0], a[1:], left=False)

    def _cmd_lpush(self, a):
        return self._push(a[0], a[1:], left=True)

    def _cmd_lpop(self, a):
        lst = self._get_list(a[0])
        return self._bulk(lst.pop(0) if lst else None)

    def _cmd_rpop(self, a):
        lst = self._get_list(a[0])
        return self._bulk(lst.pop() if lst else None)

    def _cmd_llen(self, a):
        lst = self._get_list(a[0])
        return self._int(len(lst) if lst else 0)

    def _cmd_lrange(self, a):
        lst = self._get_list(a[0]) or []
        start, stop = int(a[1]), int(a[2])
        n = len(lst)
        if start < 0:
            start += n
        if stop < 0:
            stop += n
        return self._arr(lst[max(0, start):stop + 1])

    def _cmd_blpop(self, a):
        """Blocking pop; a = [key, timeout_s]. Runs outside the dispatch
        lock — takes it via the condition."""
        key, timeout_s = a[0], float(a[1])
        deadline = None if timeout_s == 0 else _now() + timeout_s
        st = self.store
        with st.push_cond:
            while not self._stop.is_set():
                lst = self._get_list(key)
                if lst:
                    return self._arr([key, lst.pop(0)])
                remaining = None if deadline is None else deadline - _now()
                if remaining is not None and remaining <= 0:
                    return b"*-1\r\n"
                st.push_cond.wait(
                    timeout=min(0.5, remaining) if remaining else 0.5)
            return b"*-1\r\n"


class _Error(Exception):
    pass
