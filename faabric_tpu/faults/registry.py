"""Seedable, deterministic fault-injection registry.

Named fault points are armed with rules parsed from a spec string
(``FAABRIC_FAULTS`` env var at process boot, or programmatically via
``install_faults`` in tests). Call sites hold a module-level handle and
gate on a boot-time bool, so with faults disabled the hot path pays one
global-load + branch and nothing else — the same cost model as the
telemetry registry's shared no-op handles.

Spec grammar (``;``-separated rules)::

    spec   := rule (';' rule)*
    rule   := point '=' action (':' arg)? ('@' mod)*
    point  := dotted fault-point name   e.g. transport.send
    action := delay | drop | raise | kill_conn | suppress
    arg    := delay: duration ('50ms', '0.5s', bare seconds)
              raise: the exception message
    mod    := p=<float>      fire with this probability (seeded RNG)
            | after=<int>    skip the first N arrivals
            | times=<int>    fire at most N times, then disarm
            | <key>=<value>  fire only when fire(key=...) ctx matches
                             (substring match on str(value))

Examples::

    FAABRIC_FAULTS="transport.send=delay:50ms@p=0.1"
    FAABRIC_FAULTS="planner.dispatch=kill_conn@times=1;keepalive=suppress@host=w2"

Host-pair rules (network partitions): every fire() is implicitly
stamped with ``src=<this process's host identity>`` (set by the worker
runtime / planner at boot via :func:`set_fault_identity`), so one
cluster-wide spec can partition a specific DIRECTED pair::

    # drop w0 -> w1 only; w1 -> w0 still flows
    FAABRIC_FAULTS="transport.send=drop@src=w0@host=w1"
    # both directions: one rule per direction
    FAABRIC_FAULTS="transport.send=drop@src=w0@host=w1;transport.send=drop@src=w1@host=w0"

Clearing the rules (``clear_faults``, or a ``times=`` budget running
out) heals the partition — call sites re-dial on their next attempt.

Determinism: every rule owns a ``random.Random`` seeded from
``(FAABRIC_FAULTS_SEED, point, rule index)``, so a given spec + seed
fires identically run to run regardless of thread interleaving at other
points.

Actions:

- ``delay`` sleeps, then lets the operation proceed;
- ``raise`` raises :class:`FaultInjected`;
- ``kill_conn`` raises :class:`FaultConnectionError` (a
  ``ConnectionError``, so transport error handling treats it exactly
  like a peer reset and exercises reconnect/retry paths);
- ``drop`` / ``suppress`` return the :data:`DROP` / :data:`SUPPRESS`
  verdict, which the call site interprets (skip the send, skip the
  keep-alive, ...).

State-plane points (ISSUE 19, same grammar): ``state.pull`` and
``state.push`` fire on a replica's remote chunk pulls/pushes,
``state.replicate`` on the master's synchronous forward to its backup.
All three map a ``drop`` verdict to a raised
:class:`FaultConnectionError` — a dropped state RPC is
indistinguishable from a dead peer, so the retry / no-ack machinery is
what gets exercised, not a silent skip::

    # fail the first backup forward, then heal
    FAABRIC_FAULTS="state.replicate=drop@times=1"
    # every pull from key a/k times out at the client
    FAABRIC_FAULTS="state.pull=kill_conn@key=a/k"
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Optional

from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

# Verdicts returned by fire(); compared by identity at call sites.
DROP = "drop"
SUPPRESS = "suppress"

_ACTIONS = ("delay", "drop", "raise", "kill_conn", "suppress")


class FaultInjected(RuntimeError):
    """Raised by a ``raise`` fault rule."""


class FaultConnectionError(ConnectionError):
    """Raised by a ``kill_conn`` fault rule. Subclasses ConnectionError
    (hence OSError) so transport except-clauses treat it as a real peer
    failure."""


class _NullFaultPoint:
    """Shared no-op handle returned while fault injection is disabled."""

    __slots__ = ()
    name = ""
    active = False

    def fire(self, **ctx) -> Optional[str]:
        return None


NULL_FAULT = _NullFaultPoint()


def _parse_duration(text: str) -> float:
    text = text.strip()
    if text.endswith("ms"):
        return float(text[:-2]) / 1000.0
    if text.endswith("s"):
        return float(text[:-1])
    return float(text)


class FaultRule:
    """One armed rule on one fault point."""

    def __init__(self, point: str, action: str, arg: str = "",
                 p: float = 1.0, after: int = 0,
                 times: Optional[int] = None,
                 matchers: Optional[dict[str, str]] = None,
                 seed: int = 0, index: int = 0) -> None:
        if action not in _ACTIONS:
            raise ValueError(f"unknown fault action {action!r} "
                             f"(expected one of {_ACTIONS})")
        self.point = point
        self.action = action
        self.arg = arg
        self.delay_seconds = _parse_duration(arg) if action == "delay" else 0.0
        self.p = p
        self.after = after
        self.times = times
        self.matchers = matchers or {}
        self._lock = threading.Lock()
        self.arrivals = 0
        self.fired = 0
        # Per-rule RNG: deterministic for a fixed (seed, point, index)
        # and immune to draws at other points/rules
        self._rng = random.Random(f"{seed}:{point}:{index}")

    def matches(self, ctx: dict) -> bool:
        for key, want in self.matchers.items():
            if want not in str(ctx.get(key, "")):
                return False
        return True

    def should_fire(self, ctx: dict) -> bool:
        """Arrival-counting + probability gate; True → apply()."""
        if self.matchers and not self.matches(ctx):
            return False
        with self._lock:
            self.arrivals += 1
            if self.arrivals <= self.after:
                return False
            if self.times is not None and self.fired >= self.times:
                return False
            if self.p < 1.0 and self._rng.random() >= self.p:
                return False
            self.fired += 1
            return True

    def apply(self, ctx: dict) -> Optional[str]:
        logger.warning("FAULT %s: %s%s fired (ctx=%s)", self.point,
                       self.action, f":{self.arg}" if self.arg else "", ctx)
        if self.action == "delay":
            time.sleep(self.delay_seconds)
            return None
        if self.action == "drop":
            return DROP
        if self.action == "suppress":
            return SUPPRESS
        if self.action == "kill_conn":
            raise FaultConnectionError(
                f"injected connection failure at {self.point}")
        raise FaultInjected(
            f"{self.point}: {self.arg or 'injected fault'}")

    def to_dict(self) -> dict:
        return {"point": self.point, "action": self.action, "arg": self.arg,
                "p": self.p, "after": self.after, "times": self.times,
                "matchers": dict(self.matchers),
                "arrivals": self.arrivals, "fired": self.fired}


def parse_fault_spec(spec: str, seed: int = 0) -> list[FaultRule]:
    """Parse a FAABRIC_FAULTS spec into rules; raises ValueError on a
    malformed spec (a silently-ignored chaos spec would fake a green
    chaos run)."""
    rules: list[FaultRule] = []
    for index, raw in enumerate(filter(None,
                                       (r.strip() for r in spec.split(";")))):
        if "=" not in raw:
            raise ValueError(f"fault rule {raw!r} lacks 'point=action'")
        point, rest = raw.split("=", 1)
        point = point.strip()
        parts = rest.split("@")
        head, mods = parts[0], parts[1:]
        action, _, arg = head.partition(":")
        action = action.strip()
        p, after, times = 1.0, 0, None
        matchers: dict[str, str] = {}
        for mod in mods:
            if "=" not in mod:
                raise ValueError(f"fault modifier {mod!r} lacks 'key=value'")
            key, _, val = mod.partition("=")
            key, val = key.strip(), val.strip()
            if key == "p":
                p = float(val)
            elif key == "after":
                after = int(val)
            elif key == "times":
                times = int(val)
            else:
                matchers[key] = val
        rules.append(FaultRule(point, action, arg.strip(), p=p, after=after,
                               times=times, matchers=matchers, seed=seed,
                               index=index))
    return rules


class FaultPoint:
    """Live handle for one named fault point. Handles are per-name
    singletons held by the registry, so rules installed later reach
    call sites that already grabbed theirs."""

    __slots__ = ("name", "_rules", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._rules: list[FaultRule] = []
        self._lock = threading.Lock()

    @property
    def active(self) -> bool:
        return bool(self._rules)

    def set_rules(self, rules: list[FaultRule]) -> None:
        with self._lock:
            self._rules = list(rules)

    def fire(self, **ctx) -> Optional[str]:
        """Evaluate this point's rules against one arrival. May sleep
        (delay), raise (raise/kill_conn) or return a DROP/SUPPRESS
        verdict; returns None when nothing fires."""
        rules = self._rules
        if not rules:
            return None
        # Stamp the firing side's host identity so rules can match a
        # directed host pair (src=..., host=/dest=...) from ONE spec
        # shared cluster-wide. Only paid when rules are armed.
        if _local_identity and "src" not in ctx:
            ctx["src"] = _local_identity
        for rule in rules:
            if rule.should_fire(ctx):
                _count_fired(self.name, rule.action)
                verdict = rule.apply(ctx)
                if verdict is not None:
                    return verdict
        return None


def _count_fired(point: str, action: str) -> None:
    # Lazy import: telemetry must not become a hard dependency of the
    # fault layer (and this only runs when a fault actually fires)
    try:
        from faabric_tpu.telemetry import (
            flight_record,
            get_metrics,
            instant,
        )

        get_metrics().counter(
            "faabric_faults_fired_total", "Injected faults fired",
            point=point, action=action).inc()
        # Visible in /trace (instant marker on the firing thread's row)
        # and in the post-mortem flight ring — an injected fault must be
        # distinguishable from a real one after the fact
        instant("faults", point, action=action)
        flight_record("fault_fired", point=point, action=action)
    except Exception:  # noqa: BLE001 — counting must never mask the fault
        pass


class FaultRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._points: dict[str, FaultPoint] = {}

    def point(self, name: str) -> FaultPoint:
        with self._lock:
            pt = self._points.get(name)
            if pt is None:
                pt = FaultPoint(name)
                self._points[name] = pt
            return pt

    def install(self, spec: str, seed: int = 0) -> None:
        """Arm the registry from a spec string (replaces prior rules)."""
        rules = parse_fault_spec(spec, seed=seed)
        by_point: dict[str, list[FaultRule]] = {}
        for r in rules:
            by_point.setdefault(r.point, []).append(r)
        with self._lock:
            names = set(self._points) | set(by_point)
        for name in names:
            self.point(name).set_rules(by_point.get(name, []))
        if rules:
            logger.warning("Fault injection armed: %s (seed=%d)", spec, seed)

    def clear(self) -> None:
        with self._lock:
            points = list(self._points.values())
        for pt in points:
            pt.set_rules([])

    def snapshot(self) -> dict:
        with self._lock:
            points = dict(self._points)
        return {name: [r.to_dict() for r in pt._rules]
                for name, pt in points.items() if pt.active}


_registry: FaultRegistry | None = None
_registry_lock = threading.Lock()

# This process's host identity, stamped into every fire() ctx as ``src``
# so host-pair (partition) rules can match direction. Set at boot by
# WorkerRuntime / PlannerServer; empty = no stamp (standalone tools).
_local_identity = ""
_identity_conflict = False


def set_fault_identity(host: str, force: bool = False) -> None:
    """Record this process's host identity for ``src=`` ctx matching.

    The stamp only makes sense when ONE runtime owns the process (the
    deployment shape for real partitions). In-process multi-host tests
    construct several runtimes side by side; the second DIFFERENT
    identity therefore clears the stamp entirely — a directed rule
    that silently matched the wrong direction would be worse than one
    that matches nothing. Tests that want a specific identity (or to
    reset the conflict latch) pass ``force=True``."""
    global _local_identity, _identity_conflict
    if force:
        _local_identity = host
        _identity_conflict = False
        return
    if _identity_conflict:
        return
    if _local_identity and host and host != _local_identity:
        logger.debug("Multiple fault identities in one process (%s, %s): "
                     "disabling src= stamping", _local_identity, host)
        _identity_conflict = True
        _local_identity = ""
        return
    _local_identity = host


def get_fault_identity() -> str:
    return _local_identity

# Boot-time switch: instrumented modules capture this (and their fault
# point handle) at import, so an unset FAABRIC_FAULTS keeps hot paths at
# a single module-global bool check. Tests flip it via
# set_faults_enabled BEFORE importing/exercising the paths under test,
# or launch subprocesses with the env var set.
_enabled = bool(os.environ.get("FAABRIC_FAULTS", ""))


def faults_enabled() -> bool:
    return _enabled


def set_faults_enabled(on: bool) -> None:
    """Test hook; production processes decide at boot via FAABRIC_FAULTS.
    Call sites gate on the value they read at import time — only modules
    imported (or handles fetched) after the flip observe the new state."""
    global _enabled
    _enabled = on


def get_fault_registry() -> FaultRegistry:
    global _registry
    if _registry is None:
        with _registry_lock:
            if _registry is None:
                _registry = FaultRegistry()
                spec = os.environ.get("FAABRIC_FAULTS", "")
                if spec:
                    seed = int(os.environ.get("FAABRIC_FAULTS_SEED", "0"))
                    _registry.install(spec, seed=seed)
    return _registry


def fault_point(name: str) -> FaultPoint | _NullFaultPoint:
    """The handle call sites hold. Shared no-op when fault injection is
    disabled (the common case): no registry, no allocation, no rules."""
    if not _enabled:
        return NULL_FAULT
    return get_fault_registry().point(name)


def install_faults(spec: str, seed: int = 0) -> None:
    """Programmatic arm (tests): enables injection and installs rules."""
    set_faults_enabled(True)
    get_fault_registry().install(spec, seed=seed)


def clear_faults() -> None:
    global _registry
    if _registry is not None:
        _registry.clear()
    set_faults_enabled(bool(os.environ.get("FAABRIC_FAULTS", "")))
