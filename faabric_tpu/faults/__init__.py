"""Deterministic fault injection for chaos testing.

One import surface over two layers:

- :mod:`faabric_tpu.faults.registry` — named fault points
  (``transport.send``, ``transport.bulk``, ``executor.run``,
  ``planner.dispatch``, ``mpi.collective``, ``keepalive``) armed from a
  ``FAABRIC_FAULTS`` spec string or programmatically, compiled to a
  shared no-op handle when disabled (same trick as telemetry/metrics.py)
  so instrumented hot paths stay free.
- :mod:`faabric_tpu.util.retry` — the RetryPolicy / CircuitBreaker pair
  the transport layer recovers with (re-exported here for discovery).

See docs/fault_tolerance.md for the spec grammar and recipes.
"""

from faabric_tpu.faults.registry import (
    DROP,
    NULL_FAULT,
    SUPPRESS,
    FaultConnectionError,
    FaultInjected,
    FaultPoint,
    FaultRegistry,
    FaultRule,
    clear_faults,
    fault_point,
    faults_enabled,
    get_fault_identity,
    get_fault_registry,
    install_faults,
    parse_fault_spec,
    set_fault_identity,
    set_faults_enabled,
)
from faabric_tpu.util.retry import CircuitBreaker, RetryPolicy

__all__ = [
    "DROP",
    "NULL_FAULT",
    "SUPPRESS",
    "CircuitBreaker",
    "FaultConnectionError",
    "FaultInjected",
    "FaultPoint",
    "FaultRegistry",
    "FaultRule",
    "RetryPolicy",
    "clear_faults",
    "fault_point",
    "faults_enabled",
    "get_fault_identity",
    "get_fault_registry",
    "install_faults",
    "parse_fault_spec",
    "set_fault_identity",
    "set_faults_enabled",
]
