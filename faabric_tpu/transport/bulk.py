"""Dedicated bulk-transfer data plane for large cross-host payloads.

Reference analog: the raw-TCP MPI data plane with OpenMPI-tuned sockets —
16 MiB send/recv buffers, TCP_NODELAY
(include/faabric/transport/tcp/Socket.h:75-78,
src/transport/tcp/SocketOptions.cpp). There every remote rank pair gets a
socket; here each (sender-host → receiver-host) pair carries all groups'
large payloads over a small set of STRIPED tuned connections, framed with
the PTP routing header, and delivers straight into the receiving broker's
queues.

Striping (ISSUE 5): one connection per peer serialized every sender
behind a single lock — with two rank threads streaming 4 MiB pipeline
chunks, half of every collective's wall time was spent queued behind the
peer's in-flight frame (the bench attribution's ``enqueue_wait``). A
client now holds one CONTROL stripe (frames under ``BULK_THRESHOLD`` and
unsequenced frames, whose per-stream FIFO must survive without sequence
numbers) plus ``BULK_STRIPES`` DATA stripes that large sequenced frames
round-robin across. Each stripe is its own socket + its own lock + its
own shm ring, so concurrent senders proceed in parallel and a large
segment never parks a small control frame behind it. Cross-stripe
reordering of one stream's frames is healed by the receiver's
sequence-numbered out-of-order buffer — the same machinery that already
merges the bulk and RPC planes.

Throughput notes (why this beats the RPC plane at 100 MiB scale):
- frames go out as ONE vectored ``sendmsg`` (header + payload views
  gathered by the kernel — no join, no extra syscall per buffer);
- the receive path reads the payload directly into one preallocated
  buffer (``recv_into``, no per-chunk bytes objects);
- a sender passes ``memoryview``s end-to-end — no reframing copy;
- 16 MiB kernel buffers keep the pipe full on high-BDP links.

Same-machine peers skip TCP entirely: each stripe announces a /dev/shm
ring (transport/shm.py) over its connection and pushes frames as one
memcpy in, one out. With a live ring, even sub-threshold DATA-channel
frames ride it (the broker routes them here — see
PointToPointBroker._send_remote), which removes the RPC plane's
per-message framing cost from same-host cross-process streams.

Ordering: bulk messages carry the same per-(group, send, recv, channel)
sequence numbers the RPC plane stamps, and land in the same broker queues
— the ordered receive path's out-of-order buffer merges planes and
stripes alike.

Adaptive wire codecs (ISSUE 11, transport/codec.py): sequenced frames
above ``CODEC_MIN_BYTES`` consult the WireCodecGovernor per link. When
it picks a non-raw codec, the frame carries a codec byte + epoch tags
in the header and the payload ships as an XOR+zlib delta against a
cached base (or a zlib/raw full frame that establishes one). Coded
streams PIN to one data stripe (hash of the stream key) so base and
delta can never reorder across stripes; shm rings never carry coded
frames (a ring memcpy beats any codec, and the governor keeps
same-machine links raw anyway). The receiver NACKs any frame it cannot
decode safely (missing/epoch-mismatched base, crc failure, decode
error) over the same connection; the sender drains NACKs before each
coded send and re-ships the named seq as a full frame — the
self-healing escape that guarantees a torn base never decodes garbage
and never stalls the stream. A stripe reconnect resets BOTH sides'
caches by construction (the receiver cache is per-connection), so
restarts and migrations degrade to full frames, not corruption.
"""

from __future__ import annotations

import errno
import os
import socket
import struct
import threading
import time

import numpy as np

from faabric_tpu.faults import fault_point, faults_enabled
from faabric_tpu.faults.registry import DROP
from faabric_tpu.telemetry import (
    NULL_FLIGHT,
    NULL_SPAN,
    get_comm_matrix,
    get_flight,
    get_metrics,
    get_perf_store,
    span,
    tracing_enabled,
)
from faabric_tpu.transport.codec import (
    CODEC_FULL,
    CODEC_LABELS,
    CODEC_MIN_BYTES,
    CODEC_RAW,
    FLAG_CACHE,
    FLAG_ESCAPE,
    ReceiverDeltaCache,
    SenderDeltaCache,
    count_escape,
    get_wire_governor,
)
from faabric_tpu.transport.common import (
    DEFAULT_SOCKET_TIMEOUT,
    resolve_host,
)
from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

_metrics = get_metrics()
_BULK_TX_FRAMES = {
    path: _metrics.counter(
        "faabric_bulk_tx_frames_total",
        "Bulk-plane frames sent", path=path)
    for path in ("tcp", "shm")
}
_BULK_TX_BYTES = {
    path: _metrics.counter(
        "faabric_bulk_tx_bytes_total",
        "Bulk-plane payload bytes sent", path=path)
    for path in ("tcp", "shm")
}
_BULK_RX_FRAMES = {
    path: _metrics.counter(
        "faabric_bulk_rx_frames_total",
        "Bulk-plane frames received", path=path)
    for path in ("tcp", "shm")
}
_BULK_RX_BYTES = {
    path: _metrics.counter(
        "faabric_bulk_rx_bytes_total",
        "Bulk-plane payload bytes received", path=path)
    for path in ("tcp", "shm")
}
_BULK_SEND_SECONDS = {
    path: _metrics.histogram(
        "faabric_bulk_send_seconds",
        "Bulk-plane per-frame send latency", path=path)
    for path in ("tcp", "shm")
}
_BULK_RECONNECTS = _metrics.counter(
    "faabric_bulk_reconnects_total",
    "Reconnect-and-resend recoveries after a stale/reset bulk connection")

# Per-(src, dst, plane) link attribution; shared no-op when metrics off.
# The flight handle is held the same way: with FAABRIC_FLIGHT=0 the
# per-frame record must not even build its kwargs dict.
_COMM = get_comm_matrix()
_FLIGHT = get_flight()
# Host-level rolling bandwidth/latency profile (ISSUE 12): each stripe
# feeds its destination HOST's link estimators alongside the rank-level
# comm matrix — the governor and schedule compiler read links, not ranks
_PERF = get_perf_store()

_FAULTS = faults_enabled()
_FP_BULK = fault_point("transport.bulk")

BULK_PORT = 8014
# Below this the RPC plane wins (no extra connection, lower latency) —
# unless the peer is same-machine with a live shm ring, where the broker
# routes ALL data-channel sizes here (a ring push beats RPC framing even
# for a 32-byte frame).
BULK_THRESHOLD = 256 * 1024
# Sanity ceiling per frame: legit traffic is chunk-pipelined well below
# this, so anything bigger is a desynced/garbage stream — and the bound
# must be small enough that np.empty(nbytes) can never OOM the host
MAX_FRAME_BYTES = 1 << 30

# Data stripes per peer (the control stripe is extra). 0 = legacy single
# connection carrying everything. The default scales with the machine:
# each stripe adds a sender lock + a server drain thread, and on a
# 2-core host the extra threads cost more in scheduler thrash than the
# parallel sockets return (measured: 1 data stripe beats 2 by ~35% on
# the cross-process allreduce there, while 8+-core hosts want several).
BULK_STRIPES = max(0, int(os.environ.get(
    "BULK_STRIPES", str(max(1, min(4, (os.cpu_count() or 2) // 2))))))
# The control stripe's ring only carries sub-threshold frames: a small
# ring keeps /dev/shm use bounded while still holding ~16 frames
CTRL_RING_BYTES = 4 * (1 << 20)

# group_hi, group_lo (group ids are 128-bit GIDs), send_idx, recv_idx,
# channel, seq, nbytes (WIRE payload length), codec, flags, _rsvd,
# base_epoch, self_epoch, crc32 (of the coded wire bytes), raw_nbytes
# (decoded payload length; == nbytes for raw frames). The codec tail is
# all-zero for raw frames and for the SHM_ANNOUNCE/SHM_RETIRE control
# sentinels — receivers act on the codec byte alone, never inference.
_FRAME = struct.Struct("<QQiiiiqBBHIIIq")
_U64 = (1 << 64) - 1


def _pack_raw(group_hi: int, group_lo: int, send_idx: int, recv_idx: int,
              channel: int, seq: int, nbytes: int) -> bytes:
    """A raw (codec-less) frame header — also used for the shm control
    sentinels, whose codec tail is zero by definition."""
    return _FRAME.pack(group_hi, group_lo, send_idx, recv_idx, channel,
                       seq, nbytes, CODEC_RAW, 0, 0, 0, 0, 0, nbytes)


# Receiver → sender back-channel record: "re-ship this seq as a full
# frame" (magic, group_hi, group_lo, send_idx, recv_idx, channel, seq).
# Rides the same TCP connection in the server→client direction, which
# otherwise only carries the one-shot shm-attach ACK at dial time.
_NACK = struct.Struct("<4sQQiiii")
_NACK_MAGIC = b"FNAK"

# Sentinel frame announcing a same-machine shm ring (transport/shm.py):
# nbytes carries the marker, seq carries the ring-name length, and the
# name follows as the payload. Real frames always have nbytes >= 0.
SHM_ANNOUNCE = -2
# Sentinel retiring the announced ring: the client abandoned it (push
# timeout / unacked), so the server must stop the drain — otherwise the
# drain thread spins on wait_data forever, pinning the unlinked mapping
# for the connection's lifetime.
SHM_RETIRE = -3

from faabric_tpu.transport.message import tune_socket as _tune  # noqa: E402


def _recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    while len(view):
        n = sock.recv_into(view, len(view))
        if n == 0:
            raise ConnectionError("bulk peer closed mid-frame")
        view = view[n:]


def _sendmsg_all(sock: socket.socket, bufs: list) -> None:
    """Vectored gather-send: the whole frame (header + payload views) in
    one syscall in the common case, looping only on partial writes."""
    views = [b if isinstance(b, memoryview) else memoryview(b)
             for b in bufs]
    remaining = sum(len(v) for v in views)
    while True:
        sent = sock.sendmsg(views)
        remaining -= sent
        if remaining <= 0:
            return
        # Drop fully-written buffers, slice the partially-written one
        while sent:
            if sent >= len(views[0]):
                sent -= len(views[0])
                views.pop(0)
            else:
                views[0] = views[0][sent:]
                sent = 0


class BulkServer:
    """Accepts bulk connections for one broker (one logical host) and
    delivers frames into its queues. Every striped client connection gets
    its own handler thread; every announced shm ring its own drain thread
    — the receive side scales with the stripes by construction."""

    # Concurrency contract (tools/concheck.py): _conns/_threads are
    # touched by start(), the accept loop and stop() concurrently;
    # _attached_rings by every conn thread. _listener/_stopping are
    # write-once-then-read (start/stop sequencing) and stay unlisted.
    GUARDS = {
        "_conns": "_lock",
        "_threads": "_lock",
        "_attached_rings": "_lock",
        "_rx_codecs": "_lock",
    }

    def __init__(self, broker, port_offset: int = 0) -> None:
        self.broker = broker
        self.port = BULK_PORT + port_offset
        self._listener: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._conns: list[socket.socket] = []
        self._lock = threading.Lock()
        self._stopping = False
        # Live per-connection receiver codec caches (each conn thread
        # owns one lazily); registered here so ops/tests can drop every
        # base at once (migration-remap simulation, memory relief)
        self._rx_codecs: list[ReceiverDeltaCache] = []
        # Ring names with a live drain (ADVICE r3): a second connection
        # announcing an already-attached name would put TWO consumers on
        # an SPSC ring — peek/pop races corrupt frames for the legitimate
        # owner, and the duplicate's cleanup unlinks the live ring
        self._attached_rings: set[str] = set()

    def start(self) -> None:
        # Sweep rings orphaned by killed peers before accepting new ones
        try:
            from faabric_tpu.transport.shm import gc_stale_rings

            gc_stale_rings()
        except Exception:  # noqa: BLE001 — GC must never block startup
            pass
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # Brief EADDRINUSE retry, same hardening as
        # MessageEndpointServer._listen: a just-torn-down fixture's
        # port (or a transient ephemeral-source squatter) must not
        # fail a startup that would succeed a moment later
        for attempt in range(10):
            try:
                s.bind(("0.0.0.0", self.port))
                break
            except OSError as e:
                if e.errno != errno.EADDRINUSE or attempt == 9:
                    s.close()
                    raise
                time.sleep(0.2)
        s.listen(64)
        self._listener = s
        t = threading.Thread(target=self._accept_loop,
                             name=f"bulk/accept@{self.port}", daemon=True)
        with self._lock:
            self._threads.append(t)
        t.start()
        logger.debug("Bulk server on :%d", self.port)

    def _accept_loop(self) -> None:
        assert self._listener is not None
        while not self._stopping:
            try:
                conn, _ = self._listener.accept()
                _tune(conn)
                conn.settimeout(None)
            except OSError:
                if self._stopping or self._listener is None:
                    return  # listener closed
                continue  # one bad connection must not kill the acceptor
            t = threading.Thread(target=self._conn_loop, args=(conn,),
                                 name="bulk/conn", daemon=True)
            with self._lock:
                self._conns.append(conn)
                # Prune finished conn threads + closed sockets so the
                # lists stay bounded under connection churn. Append AND
                # start under the lock: the old post-start append raced
                # stop()'s iteration, and an append-then-start-outside
                # would let stop() join() a not-yet-started thread
                # (RuntimeError mid-shutdown).
                self._threads = [x for x in self._threads if x.is_alive()]
                self._threads.append(t)
                self._conns = [c for c in self._conns if c.fileno() >= 0]
                t.start()

    def _conn_loop(self, conn: socket.socket) -> None:
        drain_stop = threading.Event()
        drain_thread: threading.Thread | None = None
        rx_codec: ReceiverDeltaCache | None = None
        try:
            peer_ip = conn.getpeername()[0]
        except OSError:
            peer_ip = ""
        try:
            # One preallocated header buffer per connection: every frame's
            # fixed part lands here via recv_into, no per-frame bytes
            head = bytearray(_FRAME.size)
            head_view = memoryview(head)
            while True:
                _recv_exact_into(conn, head_view[:])
                (group_hi, group_lo, send_idx, recv_idx, channel, seq,
                 nbytes, codec, flags, _rsvd, base_epoch, self_epoch,
                 crc, raw_nbytes) = _FRAME.unpack(head)
                group_id = (group_hi << 64) | group_lo
                if nbytes == SHM_ANNOUNCE and 0 < seq <= 256:
                    # Same-machine peer: attach its ring and drain it
                    # alongside this connection (ring + TCP frames are
                    # seq-merged by the receiver's ordered path). A ring
                    # is shared memory: only a LOCAL peer can legitimately
                    # announce one (the port binds 0.0.0.0, unauthenticated)
                    name_raw = bytearray(seq)
                    _recv_exact_into(conn, memoryview(name_raw))
                    if drain_thread is None and _is_local_ip(peer_ip):
                        # Fresh Event per announce: a retired drain's set()
                        # stop flag must not make a later announce's drain
                        # exit the first time the ring reads empty
                        drain_stop = threading.Event()
                        drain_thread = self._start_ring_drain(
                            name_raw.decode("utf-8", "replace"), drain_stop)
                    # ACK/NACK the attach: the client must never push a
                    # frame into a ring nothing drains (the frame would
                    # be silently lost — a seq gap the TCP fallback then
                    # cannot heal)
                    conn.sendall(b"\x01" if drain_thread is not None
                                 else b"\x00")
                    continue
                if nbytes == SHM_RETIRE:
                    # Client abandoned the ring; the drain finishes what
                    # is already buffered (stop is only honored once the
                    # ring reads empty) then exits and unlinks
                    if drain_thread is not None:
                        drain_stop.set()
                        drain_thread.join(timeout=5.0)
                        drain_thread = None
                    continue
                # Garbage (port-scanner bytes, desynced stream) must not
                # become a multi-GiB allocation or a dead thread: bound
                # the frame and drop the connection on nonsense
                if not (0 <= nbytes <= MAX_FRAME_BYTES
                        and send_idx >= 0 and recv_idx >= 0
                        and channel >= 0
                        and codec in CODEC_LABELS
                        and 0 <= raw_nbytes <= MAX_FRAME_BYTES):
                    logger.warning(
                        "Dropping bulk connection: bad frame "
                        "(nbytes=%d send=%d recv=%d chan=%d codec=%d)",
                        nbytes, send_idx, recv_idx, channel, codec)
                    return
                # np.empty skips the 100 MiB-scale memset a bytearray pays
                payload = np.empty(nbytes, dtype=np.uint8)
                _recv_exact_into(conn, memoryview(payload).cast("B"))
                _BULK_RX_FRAMES["tcp"].inc()
                _BULK_RX_BYTES["tcp"].inc(nbytes)
                if codec != CODEC_RAW:
                    # Coded stream frame: decode (and update the
                    # per-conn base cache) before delivery. An
                    # undecodable frame — missing/mismatched base, crc
                    # or decompress failure — NACKs back to the sender,
                    # which re-ships the SAME seq as a full frame; the
                    # ordered-recv path heals the gap transparently.
                    if rx_codec is None:
                        rx_codec = ReceiverDeltaCache()
                        with self._lock:
                            self._rx_codecs.append(rx_codec)
                    payload = rx_codec.decode(
                        (group_id, send_idx, recv_idx, channel), codec,
                        flags, base_epoch, self_epoch, crc, payload,
                        raw_nbytes)
                    if payload is None:
                        logger.warning(
                            "Undecodable %s frame (seq=%d base=%d); "
                            "NACKing for a full-frame escape",
                            CODEC_LABELS.get(codec, codec), seq,
                            base_epoch)
                        try:
                            conn.sendall(_NACK.pack(
                                _NACK_MAGIC, group_hi, group_lo,
                                send_idx, recv_idx, channel, seq))
                        except OSError:
                            pass  # conn dying: the reconnect heals it
                        continue
                # Deliver the array itself: it is exclusively owned by
                # this frame, so the MPI unpack can wrap it without a
                # copy. Sub-threshold frames (the shm fast path for
                # small same-machine messages) deliver as bytes — the
                # type every small-message consumer saw on the RPC plane
                if payload.size < BULK_THRESHOLD:
                    payload = payload.tobytes()
                self.broker.deliver(group_id, send_idx, recv_idx,
                                    payload, seq, channel)
        except (ConnectionError, OSError):
            pass  # peer closed / server stopping
        except Exception:  # noqa: BLE001 — one bad peer, not the server
            logger.exception("Bulk connection handler failed")
        finally:
            if rx_codec is not None:
                with self._lock:
                    try:
                        self._rx_codecs.remove(rx_codec)
                    except ValueError:
                        pass
            if drain_thread is not None:
                drain_stop.set()
                drain_thread.join(timeout=2.0)
            try:
                conn.close()
            except OSError:
                pass

    def _start_ring_drain(self, name: str,
                          stop: threading.Event) -> threading.Thread | None:
        from faabric_tpu.transport.shm import ShmRing

        with self._lock:
            if name in self._attached_rings:
                # SPSC ring: a second drain on the same name is never
                # legitimate (duplicate/forged announce) — refuse
                logger.warning("Refusing duplicate attach of live shm "
                               "ring %s", name)
                return None
            self._attached_rings.add(name)
        try:
            ring = ShmRing.attach(name)
        except (OSError, ValueError, RuntimeError) as e:
            logger.warning("Cannot attach announced shm ring %s: %s",
                           name, e)
            with self._lock:
                self._attached_rings.discard(name)
            return None
        t = threading.Thread(target=self._ring_drain_loop,
                             args=(ring, stop),
                             name=f"bulk/shm-drain@{name[-12:]}", daemon=True)
        t.start()
        return t

    # Drain batch scratch: sized so every sub-threshold frame fits but a
    # large zero-copy frame never lands in it (those take the exact-size
    # owned-array path below)
    BATCH_BUF_BYTES = BULK_THRESHOLD + _FRAME.size + 64
    BATCH_MAX_FRAMES = 64

    def _ring_drain_loop(self, ring, stop: threading.Event) -> None:
        """Pop frames (inner bulk header + payload as one ring frame)
        and deliver; blocks in the kernel (shared futex, woken by the
        producer's pushes) when idle. Bursts of small frames drain
        BATCHED: one native pop + one queue wakeup per batch instead of
        per frame (the reusable scratch is safe because sub-threshold
        payloads are copied out as bytes anyway)."""
        import ctypes as _ct

        scratch = np.empty(self.BATCH_BUF_BYTES, np.uint8)
        lens = (_ct.c_uint64 * self.BATCH_MAX_FRAMES)()
        try:
            while True:
                n = ring.pop_batch(scratch, lens, self.BATCH_MAX_FRAMES)
                if n == 0:
                    # Empty, or the next frame is a large one that
                    # cannot ride the scratch: take it exact-size (the
                    # receiver owns that array zero-copy)
                    frame = ring.try_pop()
                    if frame is None:
                        if stop.is_set():
                            return  # producer gone AND ring drained
                        ring.wait_data(20_000)
                        continue
                    if not self._deliver_ring_frame(ring, frame):
                        return
                    continue
                off = 0
                key = None
                pending: list = []
                for i in range(n):
                    ln = int(lens[i])
                    frame = scratch[off:off + ln]
                    off += ln
                    # Ring frames are always codec=RAW by construction
                    # (coded frames pin to TCP): the codec tail is
                    # ignored here
                    (group_hi, group_lo, send_idx, recv_idx, channel,
                     seq, nbytes) = _FRAME.unpack_from(frame)[:7]
                    payload = frame[_FRAME.size:ln]
                    if nbytes != len(payload):
                        # Already-popped valid frames precede this one:
                        # deliver them before abandoning, or their seqs
                        # vanish and the ordered path gets an unhealable
                        # gap for streams that arrived intact
                        if pending:
                            self.broker.deliver_many(
                                key[0], key[1], key[2], pending, key[3])
                        logger.warning("Desynced shm ring %s; abandoning",
                                       ring.name)
                        return
                    _BULK_RX_FRAMES["shm"].inc()
                    _BULK_RX_BYTES["shm"].inc(nbytes)
                    data = (payload.tobytes() if nbytes < BULK_THRESHOLD
                            else payload.copy())
                    fkey = ((group_hi << 64) | group_lo, send_idx,
                            recv_idx, channel)
                    if fkey != key:
                        if pending:
                            self.broker.deliver_many(
                                key[0], key[1], key[2], pending, key[3])
                        key, pending = fkey, []
                    pending.append((seq, data))
                if pending:
                    self.broker.deliver_many(key[0], key[1], key[2],
                                             pending, key[3])
        except Exception:  # noqa: BLE001 — one bad ring, not the server
            logger.exception("Shm ring drain failed")
        finally:
            ring.close(unlink=True)  # single-use name; clean /dev/shm
            with self._lock:
                self._attached_rings.discard(ring.name)

    def _deliver_ring_frame(self, ring, frame) -> bool:
        """Deliver one exact-size popped frame; False on a desynced
        stream (the drain abandons the ring)."""
        (group_hi, group_lo, send_idx, recv_idx, channel, seq,
         nbytes) = _FRAME.unpack_from(frame)[:7]
        payload = frame[_FRAME.size:]
        if nbytes != len(payload):
            logger.warning("Desynced shm ring %s; abandoning", ring.name)
            return False
        _BULK_RX_FRAMES["shm"].inc()
        _BULK_RX_BYTES["shm"].inc(nbytes)
        # Same small-frame contract as the TCP path: bytes below the
        # threshold, zero-copy owned arrays above it
        if nbytes < BULK_THRESHOLD:
            payload = payload.tobytes()
        self.broker.deliver((group_hi << 64) | group_lo, send_idx,
                            recv_idx, payload, seq, channel)
        return True

    def drop_codec_bases(self) -> None:
        """Ops/test hook: forget every receiver-side codec base. The
        next delta on any stream NACKs and heals via a full frame —
        exactly the epoch-mismatch path a migration remap exercises."""
        with self._lock:
            caches = list(self._rx_codecs)
        for c in caches:
            c.drop_bases()

    def stop(self) -> None:
        self._stopping = True
        if self._listener is not None:
            # shutdown() wakes the thread blocked in accept(); a bare
            # close() leaves it parked and the port held until process
            # exit (kernel keeps the socket while a syscall is in flight)
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        with self._lock:
            threads, self._threads = self._threads, []
        for t in threads:
            t.join(timeout=2.0)


def _is_local_ip(ip: str) -> bool:
    from faabric_tpu.util.network import is_local_ip

    return is_local_ip(ip)


class _Stripe:
    """One striped connection to the destination host's BulkServer: its
    own tuned socket, its own lock, its own optional shm ring. Sends on
    ONE stripe are serialized (frames must not interleave on a stream);
    sends on different stripes proceed concurrently."""

    __slots__ = ("host", "tag", "ring_bytes", "sock", "ring",
                 "ring_refused", "lock", "shm_frames", "codec_tx",
                 "nack_buf", "nack_thread", "coded_frames",
                 "escape_frames")

    # Concurrency contract: the stripe lock serializes the connection
    # AND the per-stripe state. Socket ops deliberately happen while it
    # is held — per-stripe serialization of the byte stream IS the
    # design (frames must not interleave); the broker's lock-free reads
    # of ring/ring_refused in small_frames_ok() carry line pragmas.
    # codec_tx (the sender-side base cache) carries its OWN lock and
    # GUARDS contract; lock order is stripe.lock → codec_tx._lock.
    GUARDS = {
        "sock": "lock",
        "ring": "lock",
        "ring_refused": "lock",
        "shm_frames": "lock",
        "nack_buf": "lock",
        "coded_frames": "lock",
        "escape_frames": "lock",
    }

    def __init__(self, host: str, idx: int, ring_bytes: int) -> None:
        self.host = host
        self.tag = f"{host}-s{idx}"
        self.ring_bytes = ring_bytes
        self.sock: socket.socket | None = None
        self.ring = None
        # ring_bytes <= 0 means rings are disabled by configuration:
        # pre-refusing lets small_frames_ok()'s lock-free fast path
        # cache the verdict instead of re-probing per message
        self.ring_refused = ring_bytes <= 0
        self.lock = threading.Lock()
        self.shm_frames = 0  # observability: frames that rode the ring
        # Adaptive wire codec state (transport/codec.py): the sender
        # base cache is created on the first coded frame so raw-only
        # stripes pay nothing; nack_buf reassembles the back-channel
        self.codec_tx: SenderDeltaCache | None = None
        self.nack_buf = bytearray()
        self.nack_thread: threading.Thread | None = None
        self.coded_frames = 0   # observability: frames sent non-raw
        self.escape_frames = 0  # observability: full-frame escapes

    # -- connection management (caller holds self.lock) -----------------
    def _dial_locked(self) -> socket.socket:
        from faabric_tpu.util.network import safe_create_connection

        ip, port = resolve_host(self.host, BULK_PORT)
        s = safe_create_connection((ip, port),
                                   timeout=DEFAULT_SOCKET_TIMEOUT)
        try:
            _tune(s)
            s.settimeout(None)
            self._maybe_announce_ring_locked(s, ip)
        except BaseException:
            # A failed announce (peer died mid-handshake) must not leak
            # the just-dialed socket; the caller sees the dial fail
            try:
                s.close()
            except OSError:
                pass
            raise
        return s

    def _maybe_announce_ring_locked(self, sock: socket.socket, ip: str) -> None:
        from faabric_tpu.transport import shm

        if self.ring_refused or self.ring_bytes <= 0 \
                or not _is_local_ip(ip) or not shm.shm_available():
            return
        try:
            ring = shm.ShmRing.create(self.tag, self.ring_bytes)
        except (OSError, ValueError, RuntimeError) as e:
            logger.warning("Shm ring setup for %s failed (%s); "
                           "staying on TCP", self.tag, e)
            self.ring_refused = True
            return
        name = ring.name.encode()
        try:
            # concheck: ok(blocking-under-lock) — the stripe lock IS the
            # stream serializer: the announce must not interleave with a
            # concurrent frame on this connection, and dial-time has no
            # frames queued behind it
            sock.sendall(_pack_raw(0, 0, 0, 0, 0, len(name),
                                   SHM_ANNOUNCE) + name)
        except OSError:
            # Peer gone before the announce landed: unlink the fresh
            # /dev/shm segment NOW — our pid stays alive, so the
            # stale-ring GC (creator-pid based) would never sweep it,
            # and each 30 s bulk retry would leak another ring
            ring.close(unlink=True)
            raise
        # Wait for the server's attach ACK: only an acked ring carries
        # frames (an unattached ring would swallow them silently)
        try:
            sock.settimeout(5.0)
            # concheck: ok(blocking-under-lock) — ACK read is bounded by
            # the 5 s settimeout above and happens once per dial, before
            # any sender can be queued on this fresh stripe
            ack = sock.recv(1)
        except OSError:
            ack = b""
        finally:
            sock.settimeout(None)
        if ack == b"\x01":
            self.ring = ring
        else:
            logger.warning("Bulk server did not ack shm ring for %s; "
                           "staying on TCP", self.tag)
            # If the ACK was merely lost/late, a drain may exist: retire
            # it so it never idles forever on an abandoned ring
            try:
                # concheck: ok(blocking-under-lock) — dial-time stream
                # serialization, same contract as the announce above
                sock.sendall(_pack_raw(0, 0, 0, 0, 0, 0, SHM_RETIRE))
            except OSError:
                pass
            ring.close(unlink=True)
            self.ring_refused = True

    def ensure_connected(self) -> None:
        """Dial (and announce the ring) without sending a frame — used by
        the broker to decide whether sub-threshold frames should route
        here at all."""
        with self.lock:
            if self.sock is None:
                self.sock = self._dial_locked()

    # -- the coded-stream send path (transport/codec.py) ----------------
    def send_coded(self, mode: str, group_id: int, send_idx: int,
                   recv_idx: int, seq: int, channel: int,
                   parts: list, nbytes: int) -> None:
        """Send one coded stream frame. ``parts`` are the ordered uint8
        segments of the frame payload (scatter-gather, no flatten on
        the steady-state path) — the cache flattens only when a frame
        establishes a new base, so a reconnect or NACK can always
        re-ship FULL with the same seq. Encode runs under the stripe
        lock: it serializes with the NACK drain, and coded streams are
        pinned to this stripe so base/delta order is the wire order."""
        key = (group_id, send_idx, recv_idx, channel)
        gh, gl = (group_id >> 64) & _U64, group_id & _U64
        with self.lock:
            if self.codec_tx is None:
                self.codec_tx = SenderDeltaCache()
            try:
                if self.sock is None:
                    self.sock = self._dial_locked()
                self._ensure_nack_reader_locked()
                self._process_nacks_locked()
                frame = self.codec_tx.encode(key, parts, seq, mode)
                if _FAULTS:
                    # Chaos choke point, codec flavor: kill_conn rules
                    # drive the reconnect escape below; a DROP rule
                    # matching codec= CORRUPTS the coded wire bytes
                    # (crc left stale) so the receiver integrity check
                    # + NACK heal is exercisable end-to-end
                    verdict = _FP_BULK.fire(dest=self.host,
                                            bytes=nbytes,
                                            codec=CODEC_LABELS[frame.codec])
                    if verdict is DROP and frame.codec != CODEC_FULL:
                        wire = frame.wire.copy()
                        wire[:min(8, wire.size)] ^= 0x5A
                        frame.wire = wire
                self._send_coded_frame_locked(gh, gl, send_idx, recv_idx,
                                              channel, seq, frame,
                                              group_id)
            except OSError:
                # Stale-socket recovery, coded flavor: the receiver's
                # per-conn cache died with the connection, so the only
                # safe resend is a FULL frame on a reset cache — any
                # delta would reference bases the new conn never saw
                self._reset_locked()
                count_escape("reconnect")
                self.sock = self._dial_locked()
                self._ensure_nack_reader_locked()
                frame = self.codec_tx.encode(key, parts, seq, mode)
                try:
                    self._send_coded_frame_locked(
                        gh, gl, send_idx, recv_idx, channel, seq, frame,
                        group_id)
                    _BULK_RECONNECTS.inc()
                except BaseException:
                    self._reset_locked()
                    raise

    def _send_coded_frame_locked(self, gh: int, gl: int, send_idx: int,
                                 recv_idx: int, channel: int, seq: int,
                                 frame, group_id: int) -> None:
        wire = frame.wire
        label = CODEC_LABELS[frame.codec]
        head = _FRAME.pack(gh, gl, send_idx, recv_idx, channel, seq,
                           wire.nbytes, frame.codec, frame.flags, 0,
                           frame.base_epoch, frame.self_epoch, frame.crc,
                           frame.raw_nbytes)
        t0 = time.monotonic()
        with span("transport.bulk", "tcp_send", bytes=wire.nbytes,
                  raw_bytes=frame.raw_nbytes, dest=self.host,
                  codec=label) if tracing_enabled() else NULL_SPAN:
            _sendmsg_all(self.sock, [head, wire])
        self.coded_frames += 1
        if frame.flags & FLAG_ESCAPE:
            self.escape_frames += 1
        _BULK_TX_FRAMES["tcp"].inc()
        _BULK_TX_BYTES["tcp"].inc(wire.nbytes)
        elapsed = time.monotonic() - t0
        _BULK_SEND_SECONDS["tcp"].observe(elapsed)
        _COMM.record(send_idx, recv_idx, "bulk-tcp", wire.nbytes, elapsed,
                     raw_bytes=frame.raw_nbytes, codec=label)
        _PERF.observe(self.host, "bulk-tcp", wire.nbytes, elapsed,
                      codec=label)
        if _FLIGHT is not NULL_FLIGHT:
            _FLIGHT.record("send", group=group_id, src=send_idx,
                           dst=recv_idx, plane="bulk-tcp",
                           bytes=wire.nbytes, codec=label)

    def _ensure_nack_reader_locked(self) -> None:
        """One daemon reader per live connection drains the server→
        client back-channel: a NACK must heal even if the sender never
        touches this stripe again (the blocked ordered recv on the
        other side is waiting for the escaped full frame, not for our
        next send). The reader is the ONLY socket reader after dial
        time (the shm-attach ACK is consumed before it starts), so
        records can never be split across readers."""
        t = self.nack_thread
        if t is not None and t.is_alive():
            return
        sock = self.sock
        t = threading.Thread(target=self._nack_reader, args=(sock,),
                             name=f"bulk/nack-reader@{self.tag}", daemon=True)
        self.nack_thread = t
        t.start()

    def _nack_reader(self, sock: socket.socket) -> None:
        try:
            while True:
                chunk = sock.recv(4096)
                if not chunk:
                    break  # peer closed (EOF)
                with self.lock:
                    if self.sock is not sock:
                        return  # stale reader after a reconnect
                    self.nack_buf += chunk
                    try:
                        self._process_nacks_locked()
                    except OSError:
                        # Heal resend failed mid-write: drop the conn
                        # so no later frame splices onto a torn one
                        self._reset_locked()
                        return
        except OSError:
            pass  # socket closed under us (reset/stop)
        # EOF/error: the reader is the first to LEARN the peer died
        # (a receiver restart may otherwise swallow the next frame
        # silently — a write into a dead socket only errors on the
        # round trip AFTER it). Reset now so the next send redials
        # and ships a fresh FULL frame instead of writing into limbo.
        with self.lock:
            if self.sock is sock:
                self._reset_locked()

    def _process_nacks_locked(self) -> None:
        """Re-ship each buffered NACKed seq as a FULL frame (the
        self-healing escape)."""
        if self.codec_tx is None:
            return
        while len(self.nack_buf) >= _NACK.size:
            (magic, n_gh, n_gl, n_send, n_recv, n_chan,
             n_seq) = _NACK.unpack_from(self.nack_buf)
            if magic != _NACK_MAGIC:
                # Resync by ONE byte, not a buffer clear: a late
                # shm-attach ACK (0x01 landing after the 5 s dial
                # timeout gave up on it) is a legitimate stray — real
                # NACK records behind it must still be honored
                del self.nack_buf[:1]
                continue
            del self.nack_buf[:_NACK.size]
            self._heal_nack_locked(n_gh, n_gl, n_send, n_recv, n_chan,
                                   n_seq)

    def _heal_nack_locked(self, gh: int, gl: int, send_idx: int,
                          recv_idx: int, channel: int, seq: int) -> None:
        from faabric_tpu.transport.codec import CodedFrame

        group_id = (gh << 64) | gl
        key = (group_id, send_idx, recv_idx, channel)
        got = self.codec_tx.take_for_resend(key, seq)
        if got is None:
            # Documented unhealable corner (same stance as a bulk RST
            # discarding a delivered-but-unread frame): the resend
            # window no longer holds this seq's payload — the stream
            # itself heals on its next full frame, but this seq's
            # ordered recv times out rather than hanging silently
            count_escape("lost_payload")
            logger.warning("NACK for seq %d on %s names an evicted "
                           "payload; stream heals, this seq is lost",
                           seq, self.tag)
            return
        count_escape("nack")
        base, epoch = got
        frame = CodedFrame(CODEC_FULL, FLAG_CACHE | FLAG_ESCAPE, 0,
                           epoch, 0, base, base.nbytes)
        self._send_coded_frame_locked(gh, gl, send_idx, recv_idx,
                                      channel, seq, frame, group_id)

    # -- the per-frame send path ---------------------------------------
    def send_frame(self, head: bytes, views: list, nbytes: int,
                   group_id: int, send_idx: int, recv_idx: int) -> None:
        """``head`` may be b"" when the caller pre-joined the frame
        header into views[0] (tiny-frame fast path)."""
        bufs = [head, *views] if head else views
        fired = False
        with self.lock:
            if self.sock is None:
                self.sock = self._dial_locked()
            ring = self.ring
            if ring is not None and nbytes + _FRAME.size + 8 <= ring.capacity:
                if _FAULTS:
                    # Chaos choke point, shm flavor: kill_conn raised
                    # here propagates out as a bulk outage and the
                    # broker reroutes onto the RPC plane
                    fired = True
                    _FP_BULK.fire(dest=self.host, bytes=nbytes)
                # Inner header + payload as ONE ring frame. A push
                # timeout means the server-side drain never started or
                # died (the announce is fire-and-forget): treat it as
                # ring DEATH and stay on TCP — retrying every send would
                # stall each one the full timeout while holding the
                # stripe lock (ADVICE r3). The first push gets a short
                # leash because an unattached ring can never drain.
                t0 = time.monotonic()
                # Gate attr construction too: with tracing off, the
                # per-frame fast path must not even build a kwargs dict
                with span("transport.bulk", "shm_push", bytes=nbytes,
                          dest=self.host) if tracing_enabled() \
                        else NULL_SPAN:
                    pushed = ring.push(
                        bufs,
                        timeout=2.0 if self.shm_frames == 0 else 5.0,
                        nbytes=nbytes + _FRAME.size)
                if pushed:
                    self.shm_frames += 1
                    _BULK_TX_FRAMES["shm"].inc()
                    _BULK_TX_BYTES["shm"].inc(nbytes)
                    elapsed = time.monotonic() - t0
                    _BULK_SEND_SECONDS["shm"].observe(elapsed)
                    _COMM.record(send_idx, recv_idx, "shm", nbytes,
                                 elapsed)
                    _PERF.observe(self.host, "shm", nbytes, elapsed)
                    if _FLIGHT is not NULL_FLIGHT:
                        _FLIGHT.record("send", group=group_id,
                                       src=send_idx, dst=recv_idx,
                                       plane="shm", bytes=nbytes)
                    return
                logger.warning("Shm ring for %s stalled; abandoning ring, "
                               "staying on TCP", self.tag)
                # Tell the server to stop the drain (if it is merely
                # slow, it finishes the buffered frames first — their
                # seqs precede this frame's, so ordering holds)
                try:
                    # concheck: ok(blocking-under-lock) — by design: the
                    # stripe lock serializes this connection's byte
                    # stream, so every write on it happens under the
                    # lock (see the _Stripe GUARDS contract)
                    self.sock.sendall(
                        _pack_raw(0, 0, 0, 0, 0, 0, SHM_RETIRE))
                except OSError:
                    pass
                ring.close(unlink=True)
                self.ring = None
                self.ring_refused = True
            t0 = time.monotonic()
            try:
                if _FAULTS and not fired:
                    # Chaos choke point, TCP flavor: kill_conn rules
                    # land in the except below and drive the
                    # reconnect-and-resend path, exactly like a peer
                    # that closed the keep-alive bulk connection
                    _FP_BULK.fire(dest=self.host, bytes=nbytes)
                with span("transport.bulk", "tcp_send", bytes=nbytes,
                          dest=self.host) if tracing_enabled() \
                        else NULL_SPAN:
                    _sendmsg_all(self.sock, bufs)
                _BULK_TX_FRAMES["tcp"].inc()
                _BULK_TX_BYTES["tcp"].inc(nbytes)
                elapsed = time.monotonic() - t0
                _BULK_SEND_SECONDS["tcp"].observe(elapsed)
                _COMM.record(send_idx, recv_idx, "bulk-tcp", nbytes,
                             elapsed)
                _PERF.observe(self.host, "bulk-tcp", nbytes, elapsed)
                if _FLIGHT is not NULL_FLIGHT:
                    _FLIGHT.record("send", group=group_id, src=send_idx,
                                   dst=recv_idx, plane="bulk-tcp",
                                   bytes=nbytes)
            except OSError:
                # One reconnect-and-resend attempt: the dominant failure
                # here is the STALE-SOCKET signature — the peer closed
                # the keep-alive bulk connection (worker restart, idle
                # reset) and the first write after that surfaces
                # EPIPE/ECONNRESET. Failing the collective outright for
                # that would turn a routine reconnect into a batch
                # failure. A partial frame on the dead connection is
                # discarded by the receiver with it; a frame that DID
                # fully land before the error surfaces arrives twice —
                # the ordered-recv path drops duplicate sequence
                # numbers. Known limitation: an RST that discards a
                # delivered-but-unread earlier frame on a LIVE peer
                # leaves a seq gap this retry cannot heal; ordered recvs
                # then time out rather than hang silently. (The
                # reference's raw-TCP plane has no reliability layer
                # either — its per-rank-pair sockets never reconnect, and
                # its "unacked message buffers", MpiWorld.cpp:1963-2030,
                # are the receiver-side irecv-pending queues, which this
                # framework implements in mpi/world.py's async requests.)
                self._reset_locked()
                try:
                    self.sock = self._dial_locked()
                    _sendmsg_all(self.sock, bufs)
                    _BULK_RECONNECTS.inc()
                    _BULK_TX_FRAMES["tcp"].inc()
                    _BULK_TX_BYTES["tcp"].inc(nbytes)
                    elapsed = time.monotonic() - t0
                    _BULK_SEND_SECONDS["tcp"].observe(elapsed)
                    _COMM.record(send_idx, recv_idx, "bulk-tcp", nbytes,
                                 elapsed)
                    _PERF.observe(self.host, "bulk-tcp", nbytes, elapsed)
                    if _FLIGHT is not NULL_FLIGHT:
                        _FLIGHT.record("send", group=group_id,
                                       src=send_idx, dst=recv_idx,
                                       plane="bulk-tcp", bytes=nbytes)
                except BaseException:
                    # A half-written frame must never linger on a kept
                    # socket — the receiver would splice the NEXT frame
                    # into this one's missing tail
                    self._reset_locked()
                    raise

    def _reset_locked(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
        if self.ring is not None:
            # The ring rides the connection: the server's drain stops
            # with the old conn, so a redial re-announces a fresh ring
            self.ring.close(unlink=True)
            self.ring = None
        # Codec state rides the connection too: the receiver's per-conn
        # base cache died with it, so every sender-side base is stale
        # and buffered back-channel bytes belong to the dead stream
        if self.codec_tx is not None:
            self.codec_tx.reset()
        self.nack_buf.clear()

    def close(self) -> None:
        with self.lock:
            self._reset_locked()


class BulkClient:
    """Striped connections to a destination host's BulkServer.

    Stripe 0 (CONTROL) carries frames under ``BULK_THRESHOLD`` and every
    unsequenced frame — per-stream FIFO is preserved because one stream's
    small frames never change stripe. Large sequenced frames round-robin
    across the DATA stripes (``BULK_STRIPES``, default cpu_count//2
    clamped to [1, 4]); the receiver's seq-ordered out-of-order buffer
    restores stream order, exactly as it already does when a stream
    straddles the RPC and bulk planes.

    When the destination resolves to THIS machine, each stripe switches
    its payloads to a shared-memory ring (transport/shm.py — one memcpy
    in, one out, no TCP stack): the stripe creates the ring, announces it
    over its TCP connection, and keeps TCP for frames too large for the
    ring and as the liveness signal. ``SHM_RING_BYTES`` (default 32 MiB)
    is the PER-PEER budget, split evenly across the data stripes
    (power-of-two each, 1 MiB floor); the control stripe's ring is at
    most 4 MiB on top. SHM_BULK=0 disables the rings."""

    # _rr is deliberately unlisted: the round-robin counter's data race
    # is benign (it only spreads load) and documented at the use site.
    GUARDS = {"_stripes": "_lock"}

    def __init__(self, host: str) -> None:
        self.host = host
        self._lock = threading.Lock()
        self._stripes: dict[int, _Stripe] = {}
        self._rr = 0
        # Lazily-computed shm-capability verdict for the governor (the
        # benign write race is idempotent: resolve_host is stable)
        self._local: bool | None = None

    def _stripe(self, idx: int) -> _Stripe:
        with self._lock:
            s = self._stripes.get(idx)
            if s is None:
                from faabric_tpu.transport import shm

                # SHM_RING_BYTES is the PER-PEER budget for the data
                # stripes: split it across them (rounded down to a
                # power of two, floor 1 MiB — smaller is useless for
                # large frames, which then ride TCP via the capacity
                # check) so striping does not multiply the /dev/shm
                # footprint — an 8-process same-host world maps O(k²)
                # of these ring sets. The control ring is small and
                # never exceeds the budget either.
                total = int(os.environ.get(
                    "SHM_RING_BYTES", shm.DEFAULT_RING_BYTES))
                if total <= 0:
                    # Ring budget zeroed out: disable the rings but keep
                    # the tuned bulk TCP path (a broken ring size must
                    # never read as a whole-plane outage)
                    ring_bytes = 0
                else:
                    if idx == 0 and BULK_STRIPES > 0:
                        per = min(CTRL_RING_BYTES, total)
                    else:
                        per = max(1 << 20,
                                  total // max(1, BULK_STRIPES))
                    ring_bytes = 1 << (per.bit_length() - 1)
                s = _Stripe(self.host, idx, ring_bytes)
                self._stripes[idx] = s
            return s

    def _pick(self, nbytes: int, seq: int) -> _Stripe:
        if BULK_STRIPES == 0 or nbytes < BULK_THRESHOLD or seq < 0:
            # concheck: ok(guard-unlocked) — documented lock-free
            # per-message fast path: dict.get on a GIL-atomic dict whose
            # values are only ever added, with the locked _stripe() as
            # the miss path
            s = self._stripes.get(0)
            return s if s is not None else self._stripe(0)
        # Benign data race on the counter: it only spreads load
        self._rr = rr = (self._rr + 1) % BULK_STRIPES
        s = self._stripes.get(1 + rr)  # concheck: ok(guard-unlocked)
        return s if s is not None else self._stripe(1 + rr)

    def small_frames_ok(self) -> bool:
        """True when sub-threshold frames should route here: the control
        stripe has (or can establish) a live shm ring. Dials on first
        use; OSErrors propagate so the broker can mark the plane down."""
        # Lock-free fast path — this runs per small message once the
        # ring is up, and must cost a dict read + an attribute read
        # concheck: ok(guard-unlocked) — same GIL-atomic add-only dict
        # contract as _pick; ring/ring_refused reads are monotonic flags
        s = self._stripes.get(0)
        if s is not None:
            if s.ring is not None:
                return True
            if s.ring_refused:
                return False
        s = self._stripe(0)
        s.ensure_connected()
        return s.ring is not None

    # -- observability / test handles -----------------------------------
    @property
    def shm_frames(self) -> int:
        with self._lock:
            return sum(s.shm_frames for s in self._stripes.values())

    def rings(self) -> list:
        with self._lock:
            return [s.ring for s in self._stripes.values()
                    if s.ring is not None]

    def stripes(self) -> list:
        with self._lock:
            return list(self._stripes.values())

    def is_local(self) -> bool:
        """Whether the destination resolves to this machine (the
        shm-capable link class the governor keeps raw)."""
        local = self._local
        if local is None:
            from faabric_tpu.transport.common import host_is_local

            local = self._local = host_is_local(self.host)
        return local

    def _pin_idx(self, group_id: int, send_idx: int, recv_idx: int,
                 channel: int) -> int:
        """Deterministic stripe for a CODED stream: base and delta
        frames must share one FIFO connection (cross-stripe reordering
        would make every other delta arrive before its base)."""
        if BULK_STRIPES == 0:
            return 0
        mix = (group_id ^ (send_idx * 1000003) ^ (recv_idx * 8191)
               ^ (channel * 127))
        return 1 + (mix % BULK_STRIPES)

    # -- observability / test handles -----------------------------------
    @property
    def coded_frames(self) -> int:
        with self._lock:
            return sum(s.coded_frames for s in self._stripes.values())

    @property
    def escape_frames(self) -> int:
        with self._lock:
            return sum(s.escape_frames for s in self._stripes.values())

    def send(self, group_id: int, send_idx: int, recv_idx: int,
             bufs, seq: int, channel: int) -> None:
        """``bufs``: list of bytes-like buffers forming one frame payload —
        sent scatter-gather style straight from the caller's memory."""
        views = [memoryview(b).cast("B") if not isinstance(b, memoryview)
                 else b.cast("B") for b in bufs]
        nbytes = sum(len(v) for v in views)
        if seq >= 0 and nbytes >= CODEC_MIN_BYTES:
            # Adaptive wire codec (transport/codec.py): the governor's
            # verdict rides the frame header, so the receiver decodes
            # what the header says — per-link, per-window, never
            # inferred. Only sequenced frames are eligible (the escape
            # protocol heals by re-shipping a seq) and live shm rings
            # always win over any codec.
            mode = get_wire_governor().bulk_codec(
                self.host, self.is_local(), send_idx, recv_idx, nbytes)
            if mode != "raw":
                stripe = self._stripe(self._pin_idx(
                    group_id, send_idx, recv_idx, channel))
                # concheck: ok(guard-unlocked) — monotonic ring flag
                # read, same contract as small_frames_ok: a ring that
                # appears after this check only delays coding by one
                # frame, never corrupts it
                if stripe.ring is None:
                    parts = [np.frombuffer(v, dtype=np.uint8)
                             for v in views]
                    stripe.send_coded(mode, group_id, send_idx,
                                      recv_idx, seq, channel, parts,
                                      nbytes)
                    return
        head = _pack_raw((group_id >> 64) & _U64, group_id & _U64,
                         send_idx, recv_idx, channel, seq, nbytes)
        if nbytes < 4096:
            # Pre-join tiny frames: one buffer through the gather paths
            # (ring pushv / sendmsg) costs less than three pointer
            # conversions, and the join itself is ~100 ns at this size
            views = [memoryview(b"".join((head, *views)))]
            head = b""
        self._pick(nbytes, seq).send_frame(head, views, nbytes,
                                           group_id, send_idx, recv_idx)

    def close(self) -> None:
        with self._lock:
            stripes, self._stripes = list(self._stripes.values()), {}
        for s in stripes:
            s.close()
