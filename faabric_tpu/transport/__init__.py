from faabric_tpu.transport.message import (
    TransportMessage,
    MessageResponseCode,
    SHUTDOWN_CODE,
)
from faabric_tpu.transport.common import (
    STATE_ASYNC_PORT,
    STATE_SYNC_PORT,
    FUNCTION_CALL_ASYNC_PORT,
    FUNCTION_CALL_SYNC_PORT,
    SNAPSHOT_ASYNC_PORT,
    SNAPSHOT_SYNC_PORT,
    POINT_TO_POINT_ASYNC_PORT,
    POINT_TO_POINT_SYNC_PORT,
    PLANNER_ASYNC_PORT,
    PLANNER_SYNC_PORT,
    MPI_BASE_PORT,
    register_host_alias,
    resolve_host,
    clear_host_aliases,
)
from faabric_tpu.transport.server import MessageEndpointServer
from faabric_tpu.transport.client import MessageEndpointClient

__all__ = [
    "TransportMessage",
    "MessageResponseCode",
    "SHUTDOWN_CODE",
    "MessageEndpointServer",
    "MessageEndpointClient",
    "register_host_alias",
    "resolve_host",
    "clear_host_aliases",
    "STATE_ASYNC_PORT",
    "STATE_SYNC_PORT",
    "FUNCTION_CALL_ASYNC_PORT",
    "FUNCTION_CALL_SYNC_PORT",
    "SNAPSHOT_ASYNC_PORT",
    "SNAPSHOT_SYNC_PORT",
    "POINT_TO_POINT_ASYNC_PORT",
    "POINT_TO_POINT_SYNC_PORT",
    "PLANNER_ASYNC_PORT",
    "PLANNER_SYNC_PORT",
    "MPI_BASE_PORT",
]

from faabric_tpu.transport.point_to_point import (  # noqa: E402
    POINT_TO_POINT_MAIN_IDX,
    PointToPointBroker,
    PointToPointGroup,
    mappings_from_decision,
)
from faabric_tpu.transport.ptp_remote import (  # noqa: E402
    PointToPointCall,
    PointToPointClient,
    PointToPointServer,
    clear_sent_ptp,
    get_lock_ops,
    get_sent_mappings,
    get_sent_ptp_messages,
    send_mappings_from_decision,
)
