from faabric_tpu.transport.message import (
    TransportMessage,
    MessageResponseCode,
    SHUTDOWN_CODE,
)
from faabric_tpu.transport.common import (
    STATE_ASYNC_PORT,
    STATE_SYNC_PORT,
    FUNCTION_CALL_ASYNC_PORT,
    FUNCTION_CALL_SYNC_PORT,
    SNAPSHOT_ASYNC_PORT,
    SNAPSHOT_SYNC_PORT,
    POINT_TO_POINT_ASYNC_PORT,
    POINT_TO_POINT_SYNC_PORT,
    PLANNER_ASYNC_PORT,
    PLANNER_SYNC_PORT,
    MPI_BASE_PORT,
    register_host_alias,
    resolve_host,
    clear_host_aliases,
)
from faabric_tpu.transport.server import MessageEndpointServer
from faabric_tpu.transport.client import MessageEndpointClient

__all__ = [
    "TransportMessage",
    "MessageResponseCode",
    "SHUTDOWN_CODE",
    "MessageEndpointServer",
    "MessageEndpointClient",
    "register_host_alias",
    "resolve_host",
    "clear_host_aliases",
    "STATE_ASYNC_PORT",
    "STATE_SYNC_PORT",
    "FUNCTION_CALL_ASYNC_PORT",
    "FUNCTION_CALL_SYNC_PORT",
    "SNAPSHOT_ASYNC_PORT",
    "SNAPSHOT_SYNC_PORT",
    "POINT_TO_POINT_ASYNC_PORT",
    "POINT_TO_POINT_SYNC_PORT",
    "PLANNER_ASYNC_PORT",
    "PLANNER_SYNC_PORT",
    "MPI_BASE_PORT",
]
