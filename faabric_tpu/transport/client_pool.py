"""Cached per-host RPC clients with one lifecycle.

Every layer that talks to peers (planner dispatch, snapshot pushes, state
pulls, PTP mappings) needs the same host→client cache; this is the single
implementation with a correct close/reset path.
"""

from __future__ import annotations

import threading
from typing import Callable, Generic, TypeVar

T = TypeVar("T")


class ClientPool(Generic[T]):
    # Concurrency contract (tools/concheck.py): the host→client map is
    # shared by every dispatching thread. Note close() happens OUTSIDE
    # the lock on purpose — a client close blocks on network teardown.
    GUARDS = {"_clients": "_lock"}

    def __init__(self, factory: Callable[[str], T]) -> None:
        self._factory = factory
        self._clients: dict[str, T] = {}
        self._lock = threading.Lock()

    def get(self, host: str) -> T:
        with self._lock:
            client = self._clients.get(host)
            if client is None:
                client = self._factory(host)
                self._clients[host] = client
            return client

    def drop(self, host: str) -> None:
        with self._lock:
            client = self._clients.pop(host, None)
        if client is not None:
            try:
                client.close()  # type: ignore[attr-defined]
            except Exception:  # noqa: BLE001
                pass

    def close_all(self) -> None:
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for c in clients:
            try:
                c.close()  # type: ignore[attr-defined]
            except Exception:  # noqa: BLE001
                pass

    def items(self) -> list[tuple[str, T]]:
        """Snapshot of (host, client) pairs — observability surfaces
        (e.g. the planner's /healthz breaker report) read this without
        creating clients."""
        with self._lock:
            return list(self._clients.items())

    def __len__(self) -> int:
        with self._lock:
            return len(self._clients)
