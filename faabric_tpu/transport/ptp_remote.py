"""PTP RPC: server (ports 8009/8010) + client with mock recording.

Reference analog: src/transport/PointToPointServer.cpp (async MESSAGE with
seqnum re-injection, LOCK/UNLOCK; sync MAPPING install) and
src/transport/PointToPointClient.cpp:11-145 (mock-mode recording of
messages, mappings and lock ops — the unit-test backbone).

The planner calls send_mappings_from_decision() here directly: every
scheduling decision's group mappings are pushed to all involved hosts' PTP
servers.
"""

from __future__ import annotations

import enum
import threading
from typing import TYPE_CHECKING

from faabric_tpu.batch_scheduler.decision import SchedulingDecision
from faabric_tpu.proto import PointToPointMappings
from faabric_tpu.transport.client import MessageEndpointClient
from faabric_tpu.transport.common import (
    POINT_TO_POINT_ASYNC_PORT,
    POINT_TO_POINT_SYNC_PORT,
    get_host_alias_offset,
)
from faabric_tpu.transport.message import TransportMessage
from faabric_tpu.transport.point_to_point import mappings_from_decision
from faabric_tpu.transport.server import MessageEndpointServer, handler_response
from faabric_tpu.util.config import get_system_config
from faabric_tpu.util.logging import get_logger
from faabric_tpu.util.testing import is_mock_mode

if TYPE_CHECKING:  # pragma: no cover
    from faabric_tpu.transport.point_to_point import PointToPointBroker

logger = get_logger(__name__)


class PointToPointCall(enum.IntEnum):
    MESSAGE = 1
    LOCK_GROUP = 2
    LOCK_GROUP_RECURSIVE = 3
    UNLOCK_GROUP = 4
    UNLOCK_GROUP_RECURSIVE = 5
    MAPPING = 6
    CLEAR_GROUP = 7
    ABORT_GROUP = 8


# Lock/unlock handlers run on the shared server worker pool; they must not
# park a worker for the full message timeout waiting on mappings that may
# never come, or healthy groups' traffic starves.
LOCK_MAPPING_WAIT_SECONDS = 5.0


# ---------------------------------------------------------------------------
# Mock recording (reference PointToPointClient.cpp:11-48)
# ---------------------------------------------------------------------------
_mock_lock = threading.Lock()
# (host, group_id, send_idx, recv_idx, payload)
_sent_messages: list[tuple[str, int, int, int, bytes]] = []
# (host, PointToPointMappings)
_sent_mappings: list[tuple[str, PointToPointMappings]] = []
# (call, host, group_id, group_idx)
_lock_ops: list[tuple[int, str, int, int]] = []


def get_sent_ptp_messages() -> list[tuple[str, int, int, int, bytes]]:
    with _mock_lock:
        return list(_sent_messages)


def get_sent_mappings() -> list[tuple[str, PointToPointMappings]]:
    with _mock_lock:
        return list(_sent_mappings)


def get_lock_ops() -> list[tuple[int, str, int, int]]:
    with _mock_lock:
        return list(_lock_ops)


def clear_sent_ptp() -> None:
    with _mock_lock:
        _sent_messages.clear()
        _sent_mappings.clear()
        _lock_ops.clear()


# ---------------------------------------------------------------------------

class PointToPointClient(MessageEndpointClient):
    def __init__(self, host: str) -> None:
        super().__init__(host, POINT_TO_POINT_ASYNC_PORT,
                         POINT_TO_POINT_SYNC_PORT)

    def send_mappings(self, mappings: PointToPointMappings) -> None:
        if is_mock_mode():
            with _mock_lock:
                _sent_mappings.append((self.host, mappings))
            return
        self.sync_send(int(PointToPointCall.MAPPING),
                       {"mappings": mappings.to_dict()}, idempotent=True)

    def send_mappings_many(self,
                           mappings: list[PointToPointMappings]) -> None:
        """Pipelined mapping distribution (ISSUE 8): one ASYNC RPC
        carrying every group's mappings bound for this host in a
        scheduling tick, instead of one sync MAPPING round-trip per
        group. Fire-and-forget is safe here: consumers block in
        wait_for_mappings until the mappings land, and the scheduling
        tick must not stall on each worker's apply loop (a sync wait
        per host serialized inside the tick was a measured multi-ms
        stall per tick)."""
        if not mappings:
            return
        if is_mock_mode():
            with _mock_lock:
                for m in mappings:
                    _sent_mappings.append((self.host, m))
            return
        self.async_send(int(PointToPointCall.MAPPING),
                        {"mappings_list": [m.to_dict() for m in mappings]})

    def send_message(self, group_id: int, send_idx: int, recv_idx: int,
                     data: bytes, seq: int = -1, channel: int = 0) -> None:
        if is_mock_mode():
            with _mock_lock:
                _sent_messages.append(
                    (self.host, group_id, send_idx, recv_idx, data))
            return
        self.async_send(int(PointToPointCall.MESSAGE), {
            "group_id": group_id, "send_idx": send_idx, "recv_idx": recv_idx,
            "channel": channel,
        }, data, seqnum=seq)

    def group_lock(self, app_id: int, group_id: int, group_idx: int,
                   recursive: bool = False) -> None:
        call = (PointToPointCall.LOCK_GROUP_RECURSIVE if recursive
                else PointToPointCall.LOCK_GROUP)
        if is_mock_mode():
            with _mock_lock:
                _lock_ops.append((int(call), self.host, group_id, group_idx))
            return
        self.async_send(int(call), {
            "app_id": app_id, "group_id": group_id, "group_idx": group_idx,
        })

    def group_unlock(self, app_id: int, group_id: int, group_idx: int,
                     recursive: bool = False) -> None:
        call = (PointToPointCall.UNLOCK_GROUP_RECURSIVE if recursive
                else PointToPointCall.UNLOCK_GROUP)
        if is_mock_mode():
            with _mock_lock:
                _lock_ops.append((int(call), self.host, group_id, group_idx))
            return
        self.async_send(int(call), {
            "app_id": app_id, "group_id": group_id, "group_idx": group_idx,
        })

    def clear_group(self, group_id: int) -> None:
        if is_mock_mode():
            return
        self.async_send(int(PointToPointCall.CLEAR_GROUP),
                        {"group_id": group_id})

    def clear_groups(self, group_ids: list[int]) -> None:
        """Batched group cleanup (ISSUE 8): every finished group in one
        async RPC — at high invocation QPS, one clear per completed app
        was a visible share of the planner's result-path cost."""
        if is_mock_mode() or not group_ids:
            return
        self.async_send(int(PointToPointCall.CLEAR_GROUP),
                        {"group_ids": list(group_ids)})

    def abort_group(self, group_id: int, reason: str) -> None:
        if is_mock_mode():
            return
        self.async_send(int(PointToPointCall.ABORT_GROUP),
                        {"group_id": group_id, "reason": reason})


class PointToPointServer(MessageEndpointServer):
    def __init__(self, broker: "PointToPointBroker") -> None:
        conf = get_system_config()
        offset = get_host_alias_offset(broker.host)
        super().__init__(
            POINT_TO_POINT_ASYNC_PORT + offset,
            POINT_TO_POINT_SYNC_PORT + offset,
            label=f"ptp-server-{broker.host}",
            n_threads=conf.point_to_point_server_threads,
        )
        self.broker = broker
        # Bulk data plane rides next to the RPC plane (transport/bulk.py):
        # striped clients open several connections per peer and each may
        # announce a shm ring, so the bulk server fields one handler
        # thread per connection + one drain per ring. Same-machine peers
        # route even sub-threshold data frames there (see
        # PointToPointBroker._send_remote); this RPC server keeps the
        # coordination channel and serves as every plane's fallback.
        from faabric_tpu.transport.bulk import BulkServer

        self._bulk_server = BulkServer(broker, port_offset=offset)

    def start(self) -> None:
        super().start()
        self._bulk_server.start()

    def stop(self) -> None:
        self._bulk_server.stop()
        super().stop()

    def do_async_recv(self, msg: TransportMessage) -> None:
        code = msg.code
        h = msg.header
        if code == int(PointToPointCall.MESSAGE):
            self.broker.deliver(h["group_id"], h["send_idx"], h["recv_idx"],
                                msg.payload, msg.seqnum,
                                h.get("channel", 0))
        elif code in (int(PointToPointCall.LOCK_GROUP),
                      int(PointToPointCall.LOCK_GROUP_RECURSIVE),
                      int(PointToPointCall.UNLOCK_GROUP),
                      int(PointToPointCall.UNLOCK_GROUP_RECURSIVE)):
            recursive = code in (int(PointToPointCall.LOCK_GROUP_RECURSIVE),
                                 int(PointToPointCall.UNLOCK_GROUP_RECURSIVE))
            is_lock = code in (int(PointToPointCall.LOCK_GROUP),
                               int(PointToPointCall.LOCK_GROUP_RECURSIVE))
            # Mappings may still be in flight when the first lock arrives,
            # but a missing group must not park this worker for long
            try:
                self.broker.wait_for_mappings(h["group_id"],
                                              LOCK_MAPPING_WAIT_SECONDS)
            except Exception:  # noqa: BLE001
                logger.warning("Dropping %s for unknown group %d",
                               "lock" if is_lock else "unlock", h["group_id"])
                return
            group = self.broker.get_group(h["group_id"])
            if is_lock:
                group.lock(h["group_idx"], recursive)
            else:
                group.unlock(h["group_idx"], recursive)
        elif code == int(PointToPointCall.MAPPING):
            # Async (fire-and-forget) mapping delivery: the batched
            # tick distribution plane (ISSUE 8). The sync form below
            # remains for callers that need the apply confirmed.
            for d in h.get("mappings_list") or [h["mappings"]]:
                self.broker.set_up_local_mappings_from_mappings(
                    PointToPointMappings.from_dict(d))
        elif code == int(PointToPointCall.CLEAR_GROUP):
            # Single ("group_id") or batched ("group_ids", ISSUE 8)
            for gid in h.get("group_ids") or [h["group_id"]]:
                self.broker.clear_group(gid)
        elif code == int(PointToPointCall.ABORT_GROUP):
            # propagate=False: the originator already told every member
            # host — re-broadcasting would just bounce the (idempotent)
            # abort around the group
            self.broker.abort_group(h["group_id"],
                                    h.get("reason", "remote abort"),
                                    propagate=False)
        else:
            logger.warning("Unknown async PTP call %d", code)

    def do_sync_recv(self, msg: TransportMessage) -> TransportMessage:
        if msg.code == int(PointToPointCall.MAPPING):
            # Single group ("mappings") or a whole scheduling tick's
            # worth pipelined into one call ("mappings_list", ISSUE 8)
            dicts = msg.header.get("mappings_list")
            if dicts is None:
                dicts = [msg.header["mappings"]]
            for d in dicts:
                self.broker.set_up_local_mappings_from_mappings(
                    PointToPointMappings.from_dict(d))
            return handler_response()
        raise ValueError(f"Unknown sync PTP call {msg.code}")


# ---------------------------------------------------------------------------
# Planner-side mapping distribution
# (reference PointToPointBroker::setAndSendMappingsFromSchedulingDecision)
# ---------------------------------------------------------------------------

_dist_clients: dict[str, PointToPointClient] = {}
_dist_lock = threading.Lock()


def _get_dist_client(host: str) -> PointToPointClient:
    with _dist_lock:
        client = _dist_clients.get(host)
        if client is None:
            client = PointToPointClient(host)
            _dist_clients[host] = client
        return client


def send_mappings_from_decision(decision: SchedulingDecision) -> None:
    if decision.n_messages == 0 or not decision.group_id:
        return
    mappings = mappings_from_decision(decision)
    for host in decision.unique_hosts():
        try:
            _get_dist_client(host).send_mappings(mappings)
        except Exception:  # noqa: BLE001 — a dead host must not stall others
            logger.exception("Failed sending mappings of group %d to %s",
                             decision.group_id, host)


def send_mappings_for_decisions(decisions) -> None:
    """Pipelined mapping distribution for one scheduling tick (ISSUE 8):
    group every decision's mappings by target host and deliver each
    host's set in ONE sync RPC, instead of one round-trip per (decision,
    host)."""
    per_host: dict[str, list] = {}
    for decision in decisions:
        if decision.n_messages == 0 or not decision.group_id:
            continue
        mappings = mappings_from_decision(decision)
        for host in decision.unique_hosts():
            per_host.setdefault(host, []).append(mappings)
    for host, mlist in per_host.items():
        try:
            _get_dist_client(host).send_mappings_many(mlist)
        except Exception:  # noqa: BLE001 — a dead host must not stall
            # the tick's other hosts
            logger.exception("Failed sending %d mapping set(s) to %s",
                             len(mlist), host)


def send_clear_groups(host: str, group_ids: list[int]) -> None:
    """Tell ``host`` to drop finished groups' broker state in one async
    RPC (the coalesced result plane hands these over per frame) —
    without this, long-lived workers accumulate mappings/queues per
    batch."""
    try:
        _get_dist_client(host).clear_groups(group_ids)
    except Exception:  # noqa: BLE001
        logger.debug("Failed sending clear-groups %s to %s", group_ids,
                     host)


def close_mapping_clients() -> None:
    with _dist_lock:
        for c in _dist_clients.values():
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        _dist_clients.clear()

