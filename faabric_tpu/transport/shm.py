"""Shared-memory rings: the same-machine bulk data plane.

When a bulk sender and receiver live on one physical machine (worker
processes co-located on a host, or the aliased multi-host test/bench
topology), payloads cross as ONE memcpy into a /dev/shm ring and one out
— no sockets, no kernel TCP stack, no loopback round-trips. The ring
itself is native C++ (native/shm_ring.cpp): a lock-free SPSC byte queue
whose head/tail are C++ atomics in the shared mapping — the reference
keeps same-host MPI traffic off sockets the same way with its in-process
spinlock queues (include/faabric/mpi/MpiWorld.h:29-33); this is that
design point carried across process boundaries.

Rendezvous rides the existing bulk TCP connection: the client creates
the ring file, announces its name in a sentinel frame, and the server
attaches and drains it (transport/bulk.py). The TCP connection stays
open as liveness signal and as the path for frames too large for the
ring; both planes stamp the same sequence numbers, so the receiver's
ordered path merges them.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import threading
import time

import numpy as np

from faabric_tpu.telemetry import NULL_METRIC, get_metrics
from faabric_tpu.util.native import get_shmring_lib

_metrics = get_metrics()
_RING_TX_FRAMES = _metrics.counter(
    "faabric_shm_ring_tx_frames_total", "Frames pushed into shm rings")
_RING_TX_BYTES = _metrics.counter(
    "faabric_shm_ring_tx_bytes_total", "Payload bytes pushed into shm rings")
_RING_RX_FRAMES = _metrics.counter(
    "faabric_shm_ring_rx_frames_total", "Frames popped from shm rings")
_RING_RX_BYTES = _metrics.counter(
    "faabric_shm_ring_rx_bytes_total", "Payload bytes popped from shm rings")
_RING_PUSH_WAIT = _metrics.histogram(
    "faabric_shm_ring_push_wait_seconds",
    "Blocking wait for ring space when the fast-path push found none "
    "(consumer backpressure)")
_RING_PUSH_STALLS = _metrics.counter(
    "faabric_shm_ring_push_stalls_total",
    "Ring pushes abandoned on timeout (sender fell back to TCP)")

SHM_DIR = "/dev/shm"
HDR_BYTES = 192
DEFAULT_RING_BYTES = 32 * (1 << 20)

_counter_lock = threading.Lock()
_counter = 0


def shm_available() -> bool:
    return (os.environ.get("SHM_BULK", "1") != "0"
            and os.path.isdir(SHM_DIR)
            and os.access(SHM_DIR, os.W_OK)
            and get_shmring_lib() is not None)


def gc_stale_rings() -> int:
    """Unlink rings whose creator process is gone (workers killed before
    close() leak their /dev/shm files — the name embeds the creator pid
    precisely so survivors can sweep them). Returns the count removed."""
    removed = 0
    try:
        names = os.listdir(SHM_DIR)
    except OSError:
        return 0
    for n in names:
        if not n.startswith("faabric-ring-"):
            continue
        parts = n.rsplit("-", 2)
        try:
            pid = int(parts[-2])
        except (ValueError, IndexError):
            continue
        if not os.path.exists(f"/proc/{pid}"):
            try:
                os.unlink(os.path.join(SHM_DIR, n))
                removed += 1
            except OSError:
                pass
    return removed


def _next_name(tag: str) -> str:
    global _counter
    with _counter_lock:
        _counter += 1
        n = _counter
    safe = "".join(c if c.isalnum() else "-" for c in tag)[:48]
    return f"faabric-ring-{safe}-{os.getpid()}-{n}"


class ShmRing:
    """One direction of a same-machine channel. The creating side is the
    producer; the attaching side the consumer (SPSC — exactly one of
    each, enforced by the bulk plane's one-ring-per-connection use)."""

    def __init__(self, name: str, mm: mmap.mmap, capacity: int,
                 created: bool) -> None:
        self.name = name
        self._mm = mm
        self.capacity = capacity
        self._created = created
        self._lib = get_shmring_lib()
        buf = (ctypes.c_char * (HDR_BYTES + capacity)).from_buffer(mm)
        self._base = ctypes.addressof(buf)
        self._buf = buf  # keeps the mapping pinned

    # ------------------------------------------------------------------
    @classmethod
    def create(cls, tag: str, capacity: int = DEFAULT_RING_BYTES
               ) -> "ShmRing":
        if capacity & (capacity - 1):
            raise ValueError(f"ring capacity {capacity} not a power of two")
        lib = get_shmring_lib()
        if lib is None:
            raise RuntimeError("native shm ring unavailable")
        name = _next_name(tag)
        path = os.path.join(SHM_DIR, name)
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, HDR_BYTES + capacity)
            mm = mmap.mmap(fd, HDR_BYTES + capacity)
        finally:
            os.close(fd)
        ring = cls(name, mm, capacity, created=True)
        if lib.ring_init(ring._base, capacity) != 0:
            ring.close()
            raise RuntimeError("ring_init failed")
        # Touch every page now: ftruncate hands out zero pages lazily,
        # and a fault storm inside the first big frame's memcpy would
        # bill the allocation to the hot path. (Skip page 0 — it holds
        # the just-initialized header; writing a zero would eat the
        # magic. Zeros elsewhere are what the fresh file holds anyway.)
        np.frombuffer(mm, np.uint8)[mmap.PAGESIZE::mmap.PAGESIZE] = 0
        return ring

    @classmethod
    def attach(cls, name: str) -> "ShmRing":
        lib = get_shmring_lib()
        if lib is None:
            raise RuntimeError("native shm ring unavailable")
        if "/" in name or name.startswith("."):
            raise ValueError(f"bad ring name {name!r}")
        path = os.path.join(SHM_DIR, name)
        fd = os.open(path, os.O_RDWR)
        try:
            size = os.fstat(fd).st_size
            mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        probe = (ctypes.c_char * size).from_buffer(mm)
        cap = lib.ring_check(ctypes.addressof(probe))
        del probe
        if cap < 0 or HDR_BYTES + cap != size:
            mm.close()
            raise ValueError(f"{path} is not a valid ring")
        return cls(name, mm, int(cap), created=False)

    # ------------------------------------------------------------------
    def _gather_args(self, bufs):
        """ctypes (segs, lens) for one gathered frame — built ONCE per
        push even when the blocking path retries (the conversions, not
        the native call, dominate small-frame push cost)."""
        arrs = [b if isinstance(b, np.ndarray) and b.dtype == np.uint8
                and b.ndim == 1 else np.frombuffer(b, np.uint8)
                for b in bufs]
        n = len(arrs)
        segs = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrs])
        lens = (ctypes.c_uint64 * n)(*[a.nbytes for a in arrs])
        return arrs, segs, lens, n

    def _try_pushv(self, segs, lens, n) -> bool:
        rc = self._lib.ring_try_pushv(self._base, segs, lens, n)
        if rc == -2:
            raise ValueError("frame larger than ring capacity")
        return rc == 0

    def try_push(self, bufs) -> bool:
        """One frame gathered from bytes-like segments; False when the
        ring lacks space (caller waits or falls back). Raises ValueError
        for frames that can NEVER fit."""
        _arrs, segs, lens, n = self._gather_args(bufs)
        return self._try_pushv(segs, lens, n)

    def push(self, bufs, timeout: float = 10.0,
             nbytes: int | None = None) -> bool:
        """Blocking push; False on timeout (consumer stalled — caller
        falls back to TCP). Waits in the kernel on the ring's shared
        futex, woken by the consumer's pops — no polling. Callers that
        already know the gathered size pass ``nbytes`` so the hot path
        never re-measures the buffers."""
        arrs, segs, lens, n = self._gather_args(bufs)
        if self._try_pushv(segs, lens, n):
            if _RING_TX_BYTES is not NULL_METRIC:
                _RING_TX_FRAMES.inc()
                _RING_TX_BYTES.inc(sum(lens) if nbytes is None else nbytes)
            return True
        need = (sum(lens) if nbytes is None else nbytes) + 8
        t0 = time.monotonic()
        deadline = t0 + timeout
        while True:
            self._lib.ring_wait_space(self._base, need, 20_000)
            if self._try_pushv(segs, lens, n):
                _RING_PUSH_WAIT.observe(time.monotonic() - t0)
                _RING_TX_FRAMES.inc()
                _RING_TX_BYTES.inc(need - 8)
                return True
            if time.monotonic() >= deadline:
                _RING_PUSH_WAIT.observe(time.monotonic() - t0)
                _RING_PUSH_STALLS.inc()
                return False

    def pop_batch(self, out: np.ndarray, lens, max_frames: int) -> int:
        """Pop up to ``max_frames`` consecutive frames into ``out`` (a
        caller-owned uint8 scratch buffer, reused across calls), writing
        each payload length into ``lens`` (a ctypes uint64 array). One
        native call + one futex wake per BATCH — the drain-side fast
        path for bursts of small frames. Returns the frame count; 0
        means empty OR the next frame alone exceeds ``out`` (caller
        falls back to try_pop)."""
        n = int(self._lib.ring_pop_batch(
            self._base, out.ctypes.data, out.nbytes, lens, max_frames))
        if n and _RING_RX_FRAMES is not NULL_METRIC:
            _RING_RX_FRAMES.inc(n)
            _RING_RX_BYTES.inc(int(sum(lens[i] for i in range(n))))
        return n

    def wait_data(self, timeout_us: int = 20_000) -> bool:
        """Block (kernel futex) until a frame is likely available; True
        when data is visible. Spurious wakes possible — loop try_pop."""
        return self._lib.ring_wait_data(self._base, timeout_us) == 0

    def try_pop(self) -> np.ndarray | None:
        """The next frame as a uint8 array (exclusively owned by the
        caller), or None when the ring is empty. Peek-then-pop is safe:
        this side is the only consumer, so the frame cannot change in
        between — one exact-size allocation, one copy out."""
        n = self._lib.ring_peek(self._base)
        if n < 0:
            return None
        out = np.empty(n, np.uint8)
        self._lib.ring_pop(self._base, out.ctypes.data, n)
        _RING_RX_FRAMES.inc()
        _RING_RX_BYTES.inc(n)
        return out

    def peek(self) -> int:
        """Next frame's payload length, or -1 when empty."""
        return int(self._lib.ring_peek(self._base))

    def free_space(self) -> int:
        return int(self._lib.ring_free_space(self._base))

    # ------------------------------------------------------------------
    def close(self, unlink: bool | None = None) -> None:
        """Drop the mapping; unlink defaults to whether this side created
        the file (either side may force it — the name is single-use)."""
        if self._mm is not None:
            # ctypes buffers pin the mmap; drop them first
            self._buf = None
            try:
                self._mm.close()
            except BufferError:
                pass  # a stale export keeps the map; the unlink still runs
            self._mm = None
        if unlink is None:
            unlink = self._created
        if unlink:
            try:
                os.unlink(os.path.join(SHM_DIR, self.name))
            except OSError:
                pass
