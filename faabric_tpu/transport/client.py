"""RPC client base — the MessageEndpointClient analog
(include/faabric/transport/MessageEndpointClient.h:95-133).

Holds one persistent connection per plane (async push / sync req-rep) with
lazy dial, a RetryPolicy-driven retry loop (exponential backoff + jitter,
per-peer circuit breaker — util/retry.py), and per-plane send locks.
Resolves logical hosts through the alias table so in-process multi-host
tests work (transport/common.py).

A client IS the per-peer unit: its breaker opens after
``breaker_threshold`` consecutive failures to that peer, after which
calls fail immediately (RpcError "circuit open") instead of re-paying
connect/timeout latency — bounded-time failure propagation for the
layers above (MPI abort, planner requeue).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any

from faabric_tpu.faults import DROP, fault_point, faults_enabled
from faabric_tpu.telemetry import (
    current_trace_context,
    get_metrics,
    get_perf_store,
    tracing_enabled,
)
from faabric_tpu.transport.common import DEFAULT_SOCKET_TIMEOUT, resolve_host
from faabric_tpu.transport.message import (
    MessageResponseCode,
    TransportError,
    TransportMessage,
    recv_frame,
    send_frame,
)
from faabric_tpu.util.logging import get_logger
from faabric_tpu.util.retry import RetryPolicy, default_transport_retry_policy

logger = get_logger(__name__)

_FAULTS = faults_enabled()
_FP_SEND = fault_point("transport.send")

_metrics = get_metrics()
_TX_FRAMES = {
    plane: _metrics.counter(
        "faabric_transport_tx_frames_total",
        "Frames sent on the shared RPC plane", plane=plane)
    for plane in ("async", "sync")
}
_TX_BYTES = {
    plane: _metrics.counter(
        "faabric_transport_tx_bytes_total",
        "Payload bytes sent on the shared RPC plane", plane=plane)
    for plane in ("async", "sync")
}
_RPC_SECONDS = _metrics.histogram(
    "faabric_transport_rpc_seconds",
    "Client-side sync RPC round-trip latency")
# Host-level RPC-plane profile (ISSUE 12): sync round-trips feed the
# destination host's latency estimators (and, for bulk-sized payloads,
# its bandwidth estimators) in the rolling performance-profile store
_PERF = get_perf_store()


class RpcError(Exception):
    pass


class MessageEndpointClient:
    def __init__(self, host: str, async_port: int, sync_port: int,
                 timeout: float = DEFAULT_SOCKET_TIMEOUT,
                 retry_policy: RetryPolicy | None = None) -> None:
        self.host = host
        self.async_port = async_port
        self.sync_port = sync_port
        self.timeout = timeout
        self.retry = retry_policy or default_transport_retry_policy()
        # One breaker per peer (this client IS per-peer); both planes
        # share it — a dead process is dead on both ports
        self.breaker = self.retry.new_breaker()
        self._socks: dict[str, socket.socket | None] = {"async": None, "sync": None}
        self._locks = {"async": threading.Lock(), "sync": threading.Lock()}

    def _check_breaker(self, plane: str) -> None:
        if not self.breaker.allow():
            raise RpcError(
                f"circuit open to {self.host} "
                f"({plane}; {self.breaker.threshold} consecutive failures)")

    def _dial(self, plane: str) -> socket.socket:
        from faabric_tpu.util.network import safe_create_connection

        port = self.async_port if plane == "async" else self.sync_port
        ip, real_port = resolve_host(self.host, port)
        s = safe_create_connection((ip, real_port), timeout=self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def _get_sock(self, plane: str) -> socket.socket:
        if self._socks[plane] is None:
            self._socks[plane] = self._dial(plane)
        return self._socks[plane]  # type: ignore[return-value]

    def _reset_sock(self, plane: str) -> None:
        s = self._socks[plane]
        if s is not None:
            try:
                s.close()
            except OSError:
                pass
        self._socks[plane] = None

    @staticmethod
    def _with_trace_context(header: dict[str, Any] | None) -> dict[str, Any]:
        """Stamp the active span's (trace id, span id) into the outbound
        JSON header (``_tc``) so the server's handler span links to this
        caller across the host boundary. Copy-on-write: callers may
        share header dicts."""
        if tracing_enabled():
            tc = current_trace_context()
            if tc is not None:
                header = dict(header) if header else {}
                header["_tc"] = tc
                return header
        return header or {}

    def async_send(self, code: int, header: dict[str, Any] | None = None,
                   payload: bytes = b"", seqnum: int = -1) -> int:
        """Fire-and-forget send. Returns the number of FAILED attempts
        before the frame went out (0 = clean first-try send). A
        non-zero return means the frame was re-sent on a fresh
        connection — and, crucially, that any PREVIOUS async frame on
        the old connection may have been silently lost into a dead
        peer's kernel buffer (the first write after a peer dies
        "succeeds"; only the next one errors). Callers with redelivery
        machinery (PlannerClient's recent-results window) key off it."""
        msg = TransportMessage(code=code,
                               header=self._with_trace_context(header),
                               payload=payload, seqnum=seqnum)
        with self._locks["async"]:
            self._check_breaker("async")
            last = self.retry.max_attempts - 1
            for attempt in range(self.retry.max_attempts):
                try:
                    if _FAULTS and _FP_SEND.fire(
                            host=self.host, plane="async",
                            code=code) is DROP:
                        # Injected silent loss. The caller believes the
                        # send happened, so the breaker must agree — and
                        # a half-open trial must never exit without an
                        # outcome (it would strand allow() at False)
                        self.breaker.record_success()
                        return attempt
                    send_frame(self._get_sock("async"), msg)
                    _TX_FRAMES["async"].inc()
                    _TX_BYTES["async"].inc(len(payload))
                    self.breaker.record_success()
                    return attempt
                except (OSError, TransportError) as e:
                    self._reset_sock("async")
                    self.breaker.record_failure()
                    if attempt == last:
                        raise RpcError(
                            f"async send to {self.host}:{self.async_port} failed: {e}"
                        ) from e
                    self.retry.sleep(attempt)

    def sync_send(self, code: int, header: dict[str, Any] | None = None,
                  payload: bytes = b"", idempotent: bool = False) -> TransportMessage:
        """Send a request and await its response.

        Retry discipline:
        - Failure while dialing or sending → retry once on a fresh
          connection; the request cannot have been executed.
        - Failure after the request was fully sent → NOT retried by
          default: the server may already have executed it, and a
          zero-response-bytes signature cannot distinguish "never
          delivered" from "executed but the response was lost". Callers
          whose RPC is safe to repeat pass ``idempotent=True`` to also
          retry the common stale-keep-alive signature (reused connection,
          zero response bytes, not a timeout — i.e. a server restart
          between requests).
        """
        msg = TransportMessage(code=code,
                               header=self._with_trace_context(header),
                               payload=payload)
        t0 = time.monotonic()
        with self._locks["sync"]:
            self._check_breaker("sync")
            last = self.retry.max_attempts - 1
            for attempt in range(self.retry.max_attempts):
                fresh = self._socks["sync"] is None
                sent = False
                try:
                    if _FAULTS and _FP_SEND.fire(
                            host=self.host, plane="sync",
                            code=code) is DROP:
                        # A dropped sync request has no response to wait
                        # for: surface it as the failure the caller
                        # would eventually see, bounded and honest —
                        # recorded as one, so a half-open breaker trial
                        # is never stranded without an outcome
                        self.breaker.record_failure()
                        raise RpcError(
                            f"injected drop of sync RPC {code} to "
                            f"{self.host}:{self.sync_port}")
                    sock = self._get_sock("sync")
                    attempt_t0 = time.monotonic()
                    send_frame(sock, msg)
                    sent = True
                    _TX_FRAMES["sync"].inc()
                    _TX_BYTES["sync"].inc(len(payload))
                    resp = recv_frame(sock)
                    attempt_elapsed = time.monotonic() - attempt_t0
                    self.breaker.record_success()
                    break
                except (OSError, TransportError) as e:
                    self._reset_sock("sync")
                    self.breaker.record_failure()
                    likely_stale = (
                        idempotent
                        and not fresh
                        and not isinstance(e, socket.timeout)
                        and getattr(e, "no_response_data", False)
                    )
                    if attempt == last or (sent and not likely_stale):
                        raise RpcError(
                            f"sync send to {self.host}:{self.sync_port} failed: {e}"
                        ) from e
                    self.retry.sleep(attempt)
            else:  # pragma: no cover
                raise RpcError("unreachable")
        _RPC_SECONDS.observe(time.monotonic() - t0)
        # The profile gets the SUCCESSFUL attempt's round-trip only: a
        # retry loop's backoff sleeps and failed dials measure this
        # client's patience, not the link — folding them in would let
        # one reconnect brand a healthy host as a slow link
        _PERF.observe(self.host, "ptp", len(payload), attempt_elapsed)
        if resp.response_code != int(MessageResponseCode.SUCCESS):
            raise RpcError(
                f"RPC {code} to {self.host}:{self.sync_port} failed: "
                f"{resp.header.get('error', resp.response_code)}"
            )
        return resp

    def close(self) -> None:
        self._reset_sock("async")
        self._reset_sock("sync")
