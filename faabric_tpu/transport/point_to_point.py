"""Point-to-point group messaging: broker + distributed coordination.

Reference analog: src/transport/PointToPointBroker.cpp (933 lines) —
sendMessage (:672-764), ordered recvMessage with out-of-order buffer
(:778-862), mappings from scheduling decisions (:416-478), and
PointToPointGroup lock/unlock/barrier/notify (:142-365).

The broker maps (group_id, group_idx) → (host, mpi_port, device_id) from a
SchedulingDecision and routes messages: same-host delivery lands in
in-process queues; cross-host delivery goes through PointToPointClient to
the receiving host's PointToPointServer (see ptp_remote.py).

Unlike the reference's process-singleton, a broker is instantiable per host
identity so in-process multi-host tests run several side by side. The
device ids carried in the mappings are how TPU gangs recover their chip
placement: an MPI world asks the broker for the device of each rank and
builds its ``jax.sharding`` mesh accordingly.
"""

from __future__ import annotations

import collections
import socket
import struct
import threading
import time
from typing import Optional

from faabric_tpu.batch_scheduler.decision import SchedulingDecision
from faabric_tpu.proto import PointToPointMapping, PointToPointMappings
from faabric_tpu.telemetry import (
    NULL_FLIGHT,
    flight_dump,
    flight_record,
    flow_id_for,
    get_comm_matrix,
    get_flight,
    get_metrics,
    get_tracer,
    span,
    tracing_enabled,
)
from faabric_tpu.util.config import get_system_config
from faabric_tpu.util.latch import FlagWaiter
from faabric_tpu.util.logging import get_logger
from faabric_tpu.util.queues import Queue, QueueTimeoutException

logger = get_logger(__name__)

POINT_TO_POINT_MAIN_IDX = 0
NO_LOCK_OWNER_IDX = -1
NO_SEQUENCE_NUM = -1

# How long a liveness probe's connect may take. Probes only run while a
# watched recv is already blocked past the check interval, so this sits
# on the failure path, never the hot path.
PEER_PROBE_TIMEOUT = 0.5

_GROUP_ABORTS = get_metrics().counter(
    "faabric_ptp_group_aborts_total",
    "Watched groups aborted after a peer failure")

# Per-link attribution for remote sends (telemetry/commmatrix.py); the
# handle is a shared no-op when metrics are disabled. Flight handle held
# the same way so a disabled recorder costs one identity check per send.
_COMM = get_comm_matrix()
_FLIGHT = get_flight()


class GroupAbortedError(RuntimeError):
    """A watched group (an MPI world) was aborted: a peer's host is dead
    or a send to it failed terminally. Blocked recvs/barriers raise this
    within ~one liveness-check interval instead of hanging to the raw
    socket timeout. Re-exported by the MPI layer as ``MpiWorldAborted``."""

    def __init__(self, group_id: int, reason: str = "") -> None:
        super().__init__(f"group {group_id} aborted: {reason or 'unknown'}")
        self.group_id = group_id
        self.reason = reason


# Sentinel delivered into every queue of an aborted group so blocked
# consumers wake immediately; compared by identity.
_ABORT = object()

# Channel namespaces: group coordination traffic (lock grants, barrier
# releases, notify) must never share a delivery queue with application
# payloads on the same (group, send, recv) triple — an unordered
# coordination byte could otherwise be consumed by an ordered data recv
# (and vice versa) when server workers race.
DATA_CHANNEL = 0
COORD_CHANNEL = 1


class PointToPointBroker:
    # Concurrency contract (tools/concheck.py). The broker is the
    # hottest lock in the tree, so PR 5 carved out documented LOCK-FREE
    # fast paths over GIL-atomic add-only dicts — those carry line
    # pragmas at the use sites; everything else goes through self._lock.
    GUARDS = {
        "_mappings": "_lock",
        "_flags": "_lock",
        "_queues": "_lock",
        "_sent_seq": "_lock",
        "_recv_seq": "_lock",
        "_ooo": "_lock",
        "_unseq": "_lock",
        "_groups": "_lock",
        "_clients": "_lock",
        "_bulk_clients": "_lock",
        "_bulk_down_until": "_lock",
        "_shm_peers": "_lock",
        "_watched": "_lock",
        "_aborted": "_lock",
        "_peer_ok_until": "_lock",
    }

    def __init__(self, host: str) -> None:
        self.host = host
        self._lock = threading.RLock()

        # group_id → {group_idx: mapping}
        self._mappings: dict[int, dict[int, PointToPointMapping]] = {}
        # group_id → waiter fired once mappings for the group arrive
        self._flags: dict[int, FlagWaiter] = {}
        # (group, send, recv, channel) → delivery queue of (seq, bytes)
        self._queues: dict[tuple[int, int, int, int], Queue] = {}
        # ordered-delivery state per channel
        self._sent_seq: dict[tuple[int, int, int, int], int] = {}
        self._recv_seq: dict[tuple[int, int, int, int], int] = {}
        self._ooo: dict[tuple[int, int, int, int], dict[int, bytes]] = {}
        # unsequenced messages staged by probe/ordered-recv scans
        self._unseq: dict[tuple[int, int, int, int], object] = {}

        self._groups: dict[int, PointToPointGroup] = {}
        self._clients: dict[str, object] = {}
        self._bulk_clients: dict[str, object] = {}
        self._bulk_down_until: dict[str, float] = {}
        # host → is it THIS machine with shm rings available (the rank→
        # host map decides the plane: same-machine peers get the shm
        # fast path even for sub-threshold frames)
        self._shm_peers: dict[str, bool] = {}

        # Fault propagation: groups whose blocked recvs probe the
        # expected sender's liveness (MPI worlds register themselves),
        # group → abort reason, and the probe-success cache
        self._watched: set[int] = set()
        self._aborted: dict[int, str] = {}
        self._peer_ok_until: dict[str, float] = {}

        # Out-of-band abort relay (set by the worker runtime): when the
        # direct abort broadcast cannot reach a peer — typically because
        # the abort was CAUSED by a partition of that very link — the
        # planner relays it over its own (independent) connections
        self.planner_client = None

    # ------------------------------------------------------------------
    # Mappings
    # ------------------------------------------------------------------
    def set_up_local_mappings_from_decision(
            self, decision: SchedulingDecision) -> list[str]:
        """Install this host's view of a group; returns the hosts involved
        (reference setUpLocalMappingsFromSchedulingDecision)."""
        group_id = decision.group_id
        with self._lock:
            group = self._mappings.setdefault(group_id, {})
            for m in mappings_from_decision(decision).mappings:
                group[m.group_idx] = m
            self._get_flag(group_id).set_flag()
            PointToPointGroup.add_group_if_not_exists(
                self, decision.app_id, group_id, len(group))
        return decision.unique_hosts()

    def set_up_local_mappings_from_mappings(
            self, mappings: PointToPointMappings) -> None:
        decision = SchedulingDecision.from_point_to_point_mappings(mappings)
        self.set_up_local_mappings_from_decision(decision)

    def _get_flag(self, group_id: int) -> FlagWaiter:
        with self._lock:
            flag = self._flags.get(group_id)
            if flag is None:
                # Only construct when absent: this runs per message on
                # the send/recv hot paths, and a throwaway FlagWaiter
                # (condvar + event) per call was ~10 µs of garbage
                flag = self._flags[group_id] = FlagWaiter()
            return flag

    def wait_for_mappings(self, group_id: int,
                          timeout: float | None = None) -> None:
        # Lock-free fast path: once a group's mappings are installed the
        # per-message check is one dict read + one attribute read
        # concheck: ok(guard-unlocked) — documented fast path
        flag = self._flags.get(group_id)
        if flag is not None and flag.is_set():
            return
        conf = get_system_config()
        timeout = timeout if timeout is not None else conf.global_message_timeout
        self._get_flag(group_id).wait_on_flag(timeout)

    def get_host_for_receiver(self, group_id: int, recv_idx: int) -> str:
        # Lock-free fast path (GIL-atomic dict reads): this runs twice
        # per message on the send hot path, and mapping dicts are only
        # ever replaced/extended under the lock
        # concheck: ok(guard-unlocked) — documented fast path
        group = self._mappings.get(group_id)
        if group is not None:
            m = group.get(recv_idx)
            if m is not None:
                return m.host
        with self._lock:
            return self._mappings[group_id][recv_idx].host

    def get_mpi_port_for_receiver(self, group_id: int, recv_idx: int) -> int:
        with self._lock:
            return self._mappings[group_id][recv_idx].mpi_port

    def get_device_for_idx(self, group_id: int, idx: int) -> int:
        with self._lock:
            devs = self._mappings[group_id][idx].device_ids
            return devs[0] if devs else -1

    def get_idxs_registered_for_host(self, group_id: int, host: str) -> set[int]:
        with self._lock:
            return {idx for idx, m in self._mappings.get(group_id, {}).items()
                    if m.host == host}

    def update_host_for_idx(self, group_id: int, idx: int, host: str) -> None:
        """Post-migration remap (reference updateHostForIdx)."""
        with self._lock:
            self._mappings[group_id][idx].host = host

    def group_size(self, group_id: int) -> int:
        with self._lock:
            return len(self._mappings.get(group_id, {}))

    # ------------------------------------------------------------------
    # Fault propagation (bounded-time abort for watched groups)
    # ------------------------------------------------------------------
    def watch_group(self, group_id: int) -> None:
        """Arm peer-liveness checking for a group: while one of its
        recvs blocks past ``mpi_abort_check_seconds``, the expected
        sender's host is probed; a refused connection aborts the whole
        group. MPI worlds register themselves at construction."""
        with self._lock:
            self._watched.add(group_id)

    def _is_watched(self, group_id: int) -> bool:
        # GIL-atomic set membership; per-message hot path
        return group_id in self._watched  # concheck: ok(guard-unlocked)

    def group_aborted(self, group_id: int) -> Optional[str]:
        with self._lock:
            return self._aborted.get(group_id)

    def abort_group(self, group_id: int, reason: str,
                    propagate: bool = True) -> None:
        """Mark a group aborted and wake every blocked consumer: each of
        the group's delivery queues gets an abort sentinel, and later
        recvs fail at entry. Idempotent. With ``propagate`` (the
        locally-originated case) the abort is also broadcast to every
        other host in the group's mappings, so ranks on a THIRD host —
        blocked on a live peer and therefore never probing the dead one
        — learn within one RPC instead of timing out."""
        with self._lock:
            if group_id in self._aborted:
                return
            self._aborted[group_id] = reason
            queues = [q for k, q in self._queues.items() if k[0] == group_id]
            peer_hosts = {m.host for m in
                          self._mappings.get(group_id, {}).values()
                          if m.host != self.host} if propagate else set()
        _GROUP_ABORTS.inc()
        logger.warning("Aborting group %d on %s: %s", group_id, self.host,
                       reason)
        # Black-box record: the abort transition lands in the flight ring
        # and the ring is dumped — this IS the MpiWorldAborted post-mortem
        flight_record("group_abort", group=group_id, host=self.host,
                      reason=reason)
        flight_dump("mpi_world_aborted")
        for q in queues:
            q.enqueue((NO_SEQUENCE_NUM, _ABORT))
        for host in sorted(peer_hosts):
            try:
                self._get_client(host).abort_group(group_id, reason)
            except Exception:  # noqa: BLE001 — best-effort; the planner
                # relay below covers it
                logger.debug("Could not propagate abort of group %d to %s",
                             group_id, host)
        if peer_hosts and self.planner_client is not None:
            # Belt and braces: relay through the planner for EVERY peer,
            # not just the ones whose direct send raised. On a real
            # partition the first async write onto the dead link's warm
            # connection "succeeds" into the kernel buffer and raises
            # nothing (the transport/client.py async_send hole), so an
            # exception-gated relay would miss exactly the case it
            # exists for. Receiving an abort twice is idempotent, and
            # aborts are rare — one extra async RPC is cheap.
            try:
                self.planner_client.relay_group_abort(
                    group_id, reason, sorted(peer_hosts))
            except Exception:  # noqa: BLE001 — planner down too: expiry
                # and per-peer probes remain the backstop
                logger.debug("Abort relay of group %d via planner failed",
                             group_id, exc_info=True)

    def _raise_if_aborted(self, group_id: int) -> None:
        with self._lock:
            reason = self._aborted.get(group_id)
        if reason is not None:
            raise GroupAbortedError(group_id, reason)

    def _probe_sender(self, key: tuple[int, int, int, int]) -> None:
        """Called while a watched recv is blocked: check the expected
        sender's host is still accepting connections; abort the group if
        it refuses (its process is gone — waiting out the socket timeout
        would just delay the inevitable by ~a minute)."""
        group_id, send_idx = key[0], key[1]
        with self._lock:
            m = self._mappings.get(group_id, {}).get(send_idx)
        host = m.host if m is not None else ""
        if not host or host == self.host:
            return
        if not self._peer_alive(host):
            reason = f"peer host {host} is unreachable (connection refused)"
            self.abort_group(group_id, reason)
            raise GroupAbortedError(group_id, reason)

    def _peer_alive(self, host: str) -> bool:
        """One bounded TCP dial of the peer's PTP port. Only a REFUSED
        connection counts as dead — a slow or unroutable peer keeps the
        recv waiting (its real timeout still applies). Successes are
        cached for one check interval so a stalled multi-recv collective
        probes each host once per interval, not once per recv."""
        from faabric_tpu.util.testing import is_mock_mode

        if is_mock_mode():
            return True  # no real sockets to probe in mock tests
        now = time.monotonic()
        with self._lock:
            if now < self._peer_ok_until.get(host, 0.0):
                return True
        from faabric_tpu.transport.common import (
            POINT_TO_POINT_SYNC_PORT,
            resolve_host,
        )
        from faabric_tpu.util.network import safe_create_connection

        ip, port = resolve_host(host, POINT_TO_POINT_SYNC_PORT)
        try:
            s = safe_create_connection((ip, port),
                                       timeout=PEER_PROBE_TIMEOUT)
            s.close()
        except ConnectionRefusedError:
            return False
        except OSError:
            return True  # can't tell (slow / unroutable): keep waiting
        conf = get_system_config()
        with self._lock:
            self._peer_ok_until[host] = now + conf.mpi_abort_check_seconds
        return True

    # ------------------------------------------------------------------
    # Messaging
    # ------------------------------------------------------------------
    def send_message(self, group_id: int, send_idx: int, recv_idx: int,
                     data: bytes, must_order: bool = False,
                     channel: int = DATA_CHANNEL) -> None:
        self.wait_for_mappings(group_id)
        dst_host = self.get_host_for_receiver(group_id, recv_idx)
        key = (group_id, send_idx, recv_idx, channel)

        seq = NO_SEQUENCE_NUM
        if must_order:
            with self._lock:
                seq = self._sent_seq.get(key, -1) + 1
                self._sent_seq[key] = seq

        if dst_host == self.host:
            self.deliver(group_id, send_idx, recv_idx, data, seq, channel)
        else:
            # Cross-host: a send span + a flow-start event (same
            # deterministic id the receiving host's recv derives from
            # the sequence tuple) turn the merged /trace into causal
            # send→recv arrows instead of per-host islands
            if tracing_enabled():
                with span("ptp", "send", group=group_id, src=send_idx,
                          dst=recv_idx, dest_host=dst_host,
                          bytes=len(data), remote=True):
                    if seq != NO_SEQUENCE_NUM:
                        get_tracer().flow_start(
                            flow_id_for(group_id, send_idx, recv_idx,
                                        channel, seq))
                    self._send_remote(group_id, send_idx, recv_idx, data,
                                      seq, channel, dst_host)
            else:
                self._send_remote(group_id, send_idx, recv_idx, data, seq,
                                  channel, dst_host)

    def _send_remote(self, group_id: int, send_idx: int, recv_idx: int,
                     data, seq: int, channel: int, dst_host: str) -> None:
        # Large payloads ride the dedicated bulk plane (striped tuned
        # sockets, vectored gather-send straight from the source buffers,
        # recv_into preallocated buffers — transport/bulk.py). Peers that
        # the rank→host map places on THIS machine get the shm fast path
        # for data-channel frames of ANY size (a ring push beats RPC
        # framing even for tiny frames). Peers without a bulk server fall
        # back to the RPC plane.
        from faabric_tpu.transport.bulk import (
            BULK_THRESHOLD,
            MAX_FRAME_BYTES,
        )
        from faabric_tpu.util.testing import is_mock_mode

        nbytes = len(data)
        use_bulk = BULK_THRESHOLD <= nbytes <= MAX_FRAME_BYTES
        small_shm = (not use_bulk and nbytes < BULK_THRESHOLD
                     and channel == DATA_CHANNEL
                     and self._shm_peer(dst_host))
        if ((use_bulk or small_shm) and not is_mock_mode()
                and not self._bulk_down(dst_host)):
            bufs = (data.buffers() if hasattr(data, "buffers")
                    else [data])
            try:
                # The bulk client attributes the send to the comm matrix
                # itself — it alone knows whether the frame rode the shm
                # ring or the tuned TCP connection
                client = self._get_bulk_client(dst_host)
                # Sub-threshold frames only switch plane when the
                # control stripe's ring is live — over TCP the RPC
                # plane's latency is as good and it has retry/breaker
                if use_bulk or client.small_frames_ok():
                    client.send(group_id, send_idx, recv_idx, bufs, seq,
                                channel)
                    return
            except (OSError, ValueError, struct.error) as e:
                # Remember the outage so chunk streams don't pay a
                # connect attempt (or timeout) per chunk
                self._mark_bulk_down(dst_host)
                logger.debug("Bulk send to %s unavailable (%s); using "
                             "RPC plane for %.0fs", dst_host, e,
                             self.BULK_RETRY_SECONDS)
        # Lazy wire payloads (and zero-copy local payloads re-routed
        # remote under live migration) convert to contiguous bytes
        # late, only for the RPC plane
        if not isinstance(data, (bytes, bytearray, memoryview)) \
                and hasattr(data, "to_bytes"):
            data = data.to_bytes()
        from faabric_tpu.transport.client import RpcError

        t0 = time.monotonic()
        try:
            self._get_client(dst_host).send_message(
                group_id, send_idx, recv_idx, data, seq, channel)
        except RpcError as e:
            if self._is_watched(group_id):
                # A terminally-failed send to a watched peer dooms
                # the whole group: surface one typed abort (bounded
                # — the client's retry/breaker already ran) instead
                # of letting every rank discover it separately
                reason = f"send to {dst_host} failed: {e}"
                self.abort_group(group_id, reason)
                raise GroupAbortedError(group_id, reason) from e
            raise
        _COMM.record(send_idx, recv_idx, "ptp", len(data),
                     time.monotonic() - t0)
        if _FLIGHT is not NULL_FLIGHT:
            _FLIGHT.record("send", group=group_id, src=send_idx,
                           dst=recv_idx, plane="ptp", bytes=len(data))

    def deliver(self, group_id: int, send_idx: int, recv_idx: int,
                data: bytes, seq: int = NO_SEQUENCE_NUM,
                channel: int = DATA_CHANNEL) -> None:
        """Enqueue an inbound message (local send or arriving RPC)."""
        self._get_queue((group_id, send_idx, recv_idx, channel)).enqueue(
            (seq, data))

    def deliver_many(self, group_id: int, send_idx: int, recv_idx: int,
                     items: list, channel: int = DATA_CHANNEL) -> None:
        """Batched inbound delivery for ONE key: ``items`` is an ordered
        list of (seq, data). One queue lock + one wakeup round per burst
        — the bulk drain's fast path for small-frame storms."""
        self._get_queue(
            (group_id, send_idx, recv_idx, channel)).enqueue_many(items)

    def recv_message(self, group_id: int, send_idx: int, recv_idx: int,
                     must_order: bool = False,
                     timeout: float | None = None,
                     channel: int = DATA_CHANNEL) -> bytes:
        if not tracing_enabled():
            return self._recv_message_impl(group_id, send_idx, recv_idx,
                                           must_order, timeout, channel)[0]
        # The recv span's duration IS the enqueue-wait (time this
        # consumer blocked before the message was deliverable); the
        # flow-end event (same id the sender derived from the sequence
        # tuple) closes the cross-host send→recv arrow. Emitted only
        # when the sender is REMOTE — the local send path emits no
        # flow-start, and an unmatched finish per local message would
        # evict real spans from the bounded trace ring.
        with span("ptp", "recv", group=group_id, src=send_idx,
                  dst=recv_idx):
            data, seq = self._recv_message_impl(
                group_id, send_idx, recv_idx, must_order, timeout, channel)
            if seq != NO_SEQUENCE_NUM:
                with self._lock:
                    m = self._mappings.get(group_id, {}).get(send_idx)
                if m is not None and m.host != self.host:
                    get_tracer().flow_end(
                        flow_id_for(group_id, send_idx, recv_idx, channel,
                                    seq))
            return data

    def _recv_message_impl(self, group_id: int, send_idx: int,
                           recv_idx: int, must_order: bool,
                           timeout: float | None,
                           channel: int) -> tuple[bytes, int]:
        conf = get_system_config()
        timeout = timeout if timeout is not None else conf.global_message_timeout
        key = (group_id, send_idx, recv_idx, channel)
        q = self._get_queue(key)
        watched = self._is_watched(group_id)
        if watched:
            self._raise_if_aborted(group_id)

        if not must_order:
            # A probe may have staged messages out of the raw queue;
            # drain staging (arrival order: unsequenced backlog first,
            # then buffered seqs in order) before blocking on the queue
            with self._lock:
                backlog = self._unseq.get(key)
                if backlog:
                    return backlog.popleft(), NO_SEQUENCE_NUM
                buf = self._ooo.get(key)
                if buf:
                    seq = min(buf)
                    self._recv_seq[key] = max(
                        self._recv_seq.get(key, -1), seq)
                    return buf.pop(seq), seq
            deadline = time.monotonic() + timeout
            while True:
                slice_t = max(0.0, deadline - time.monotonic())
                if watched:
                    slice_t = min(slice_t, conf.mpi_abort_check_seconds)
                try:
                    seq, data = q.dequeue(timeout=slice_t)
                except QueueTimeoutException as e:
                    if watched:
                        self._probe_sender(key)  # may abort + raise
                        self._raise_if_aborted(group_id)
                        if time.monotonic() < deadline:
                            continue
                    raise TimeoutError(
                        f"PTP recv timed out on {key}") from e
                if data is _ABORT:
                    # Abort reason is a write-once string; racing the
                    # unlocked map read is benign
                    reason = self._aborted.get(group_id, "")  # concheck: ok(guard-unlocked)
                    raise GroupAbortedError(group_id, reason)
                return data, seq

        # Ordered path: consume in seq order, buffering whatever arrives
        # early (reference PointToPointBroker.cpp:778-862). consume=True
        # takes the deliverable message in ONE pass — the common
        # already-in-order case costs two lock acquisitions per message,
        # not five (this path runs per message of every chunk stream).
        nxt = self._scan_next(key, q, timeout, consume=True)
        if nxt is None:  # only the non-blocking variant returns None
            raise TimeoutError(f"PTP ordered recv timed out on {key}")
        _kind, payload, seq = nxt
        return payload, seq

    def _scan_next(self, key, q, timeout: float | None,
                   blocking: bool = True, consume: bool = False):
        """Drain the raw queue until the next DELIVERABLE message for
        ``key`` is staged: ("seq", data) when the expected sequence
        number is buffered, ("unseq", data) when an unsequenced message
        is first in line (kept in a side backlog so probe never corrupts
        the sequence state), or None when non-blocking and nothing is
        pending. With ``consume=True`` (the ordered-recv hot path) the
        deliverable message is TAKEN and returned as ("direct", data,
        seq) — sequence state already advanced, no re-staging round
        trip. Duplicates of already-delivered seqs (bulk-plane reconnect
        resends) are dropped. Shared by ordered recv, probe and
        iprobe."""
        deadline = None if timeout is None else time.monotonic() + timeout
        watched = self._is_watched(key[0])
        check = get_system_config().mpi_abort_check_seconds if watched \
            else None
        with self._lock:
            buf = self._ooo.setdefault(key, {})
            backlog = self._unseq.setdefault(key, collections.deque())
        while True:
            with self._lock:
                if backlog:
                    if consume:
                        return ("direct", backlog.popleft(),
                                NO_SEQUENCE_NUM)
                    return ("unseq", backlog[0])
                expected = self._recv_seq.get(key, -1) + 1
                if expected in buf:
                    if consume:
                        self._recv_seq[key] = expected
                        return ("direct", buf.pop(expected), expected)
                    return ("seq", buf[expected])
            if watched:
                self._raise_if_aborted(key[0])
            if not blocking:
                item = q.try_dequeue()
                if item is None:
                    return None
            else:
                remaining = None if deadline is None else \
                    max(0.0, deadline - time.monotonic())
                slice_t = remaining
                if check is not None:
                    slice_t = check if remaining is None \
                        else min(remaining, check)
                try:
                    item = q.dequeue(timeout=slice_t)
                except QueueTimeoutException:
                    if watched:
                        self._probe_sender(key)  # may abort + raise
                        self._raise_if_aborted(key[0])
                        if deadline is None or time.monotonic() < deadline:
                            continue
                    return None
            seq, data = item
            if data is _ABORT:
                # Abort reason is a write-once string; racing the
                # unlocked map read is benign
                reason = self._aborted.get(key[0], "")  # concheck: ok(guard-unlocked)
                raise GroupAbortedError(key[0], reason)
            with self._lock:
                if seq == NO_SEQUENCE_NUM:
                    if consume and not backlog:
                        return ("direct", data, NO_SEQUENCE_NUM)
                    backlog.append(data)
                elif seq <= self._recv_seq.get(key, -1):
                    pass  # duplicate already delivered: drop
                elif (consume and not backlog
                        and seq == self._recv_seq.get(key, -1) + 1):
                    # The just-dequeued message IS the next in order:
                    # hand it over without the buffer round trip
                    self._recv_seq[key] = seq
                    return ("direct", data, seq)
                else:
                    buf[seq] = data

    def probe_message(self, group_id: int, send_idx: int, recv_idx: int,
                      timeout: float | None = None,
                      channel: int = DATA_CHANNEL):
        """Peek the next deliverable message without consuming it (MPI
        probe). Blocks up to ``timeout``; raises TimeoutError."""
        conf = get_system_config()
        timeout = timeout if timeout is not None else conf.global_message_timeout
        key = (group_id, send_idx, recv_idx, channel)
        nxt = self._scan_next(key, self._get_queue(key), timeout)
        if nxt is None:
            raise TimeoutError(f"PTP probe timed out on {key}")
        return nxt[1]

    def try_probe_message(self, group_id: int, send_idx: int, recv_idx: int,
                          channel: int = DATA_CHANNEL):
        """Non-blocking probe: the next deliverable message or None."""
        key = (group_id, send_idx, recv_idx, channel)
        nxt = self._scan_next(key, self._get_queue(key), None,
                              blocking=False)
        return None if nxt is None else nxt[1]

    def _get_queue(self, key: tuple[int, int, int, int]) -> Queue:
        # concheck: ok(guard-unlocked) — documented fast path
        q = self._queues.get(key)  # lock-free per-message path
        if q is not None:
            return q
        with self._lock:
            q = self._queues.get(key)
            if q is None:
                q = Queue()
                self._queues[key] = q
            return q

    # ------------------------------------------------------------------
    # Groups
    # ------------------------------------------------------------------
    def get_group(self, group_id: int) -> "PointToPointGroup":
        with self._lock:
            group = self._groups.get(group_id)
            if group is None:
                raise KeyError(f"Group {group_id} not registered on {self.host}")
            return group

    def group_exists(self, group_id: int) -> bool:
        with self._lock:
            return group_id in self._groups

    def clear_group(self, group_id: int) -> None:
        with self._lock:
            self._groups.pop(group_id, None)
            self._mappings.pop(group_id, None)
            self._flags.pop(group_id, None)
            self._watched.discard(group_id)
            self._aborted.pop(group_id, None)
            for key in [k for k in self._queues if k[0] == group_id]:
                del self._queues[key]
            for d in (self._sent_seq, self._recv_seq, self._ooo,
                      self._unseq):
                for key in [k for k in d if k[0] == group_id]:
                    del d[key]

    def post_migration_hook(self, group_id: int, group_idx: int) -> None:
        """Re-sync a migrated group: every member barriers on the NEW group
        id so no rank races ahead with stale mappings (reference
        postMigrationHook :910-928; MPI worlds re-init on top of this)."""
        self.wait_for_mappings(group_id)
        self.get_group(group_id).barrier(group_idx)

    def clear(self) -> None:
        with self._lock:
            self._groups.clear()
            self._mappings.clear()
            self._flags.clear()
            self._queues.clear()
            self._sent_seq.clear()
            self._recv_seq.clear()
            self._ooo.clear()
            self._unseq.clear()
            self._watched.clear()
            self._aborted.clear()
            self._peer_ok_until.clear()
            for c in list(self._clients.values()) \
                    + list(self._bulk_clients.values()):
                try:
                    c.close()
                except Exception:  # noqa: BLE001
                    pass
            self._clients.clear()
            self._bulk_clients.clear()
            self._shm_peers.clear()

    def _get_client(self, host: str):
        # concheck: ok(guard-unlocked) — documented fast path
        client = self._clients.get(host)  # lock-free per-message path
        if client is not None:
            return client
        from faabric_tpu.transport.ptp_remote import PointToPointClient

        with self._lock:
            if host not in self._clients:
                self._clients[host] = PointToPointClient(host)
            return self._clients[host]

    def _get_bulk_client(self, host: str):
        # concheck: ok(guard-unlocked) — documented fast path
        client = self._bulk_clients.get(host)  # lock-free per-message path
        if client is not None:
            return client
        from faabric_tpu.transport.bulk import BulkClient

        with self._lock:
            if host not in self._bulk_clients:
                self._bulk_clients[host] = BulkClient(host)
            return self._bulk_clients[host]

    # Bulk-plane outage cache: after a failed send, skip the bulk plane
    # for this long rather than re-dialing per payload/chunk
    BULK_RETRY_SECONDS = 30.0

    def _shm_peer(self, host: str) -> bool:
        """True when the rank→host map's ``host`` is this same machine
        and shm rings are usable — the selection rule for the shm fast
        path. Cached per host (alias resolution + /dev/shm probe); the
        cached read is lock-free (GIL-atomic dict get, per-message)."""
        cached = self._shm_peers.get(host)  # concheck: ok(guard-unlocked)
        if cached is not None:
            return cached
        from faabric_tpu.transport import shm
        from faabric_tpu.transport.common import resolve_host
        from faabric_tpu.util.network import is_local_ip

        try:
            result = (shm.shm_available()
                      and is_local_ip(resolve_host(host, 0)[0]))
        except Exception:  # noqa: BLE001 — unresolvable host: not local
            result = False
        with self._lock:
            self._shm_peers[host] = result
        return result

    def _bulk_down(self, host: str) -> bool:
        # GIL-atomic dict read — this runs per message on the send hot
        # path now that small frames route through the bulk plane
        until = self._bulk_down_until.get(host, 0.0)  # concheck: ok(guard-unlocked)
        return until > 0.0 and time.monotonic() < until

    def _mark_bulk_down(self, host: str) -> None:
        with self._lock:
            self._bulk_down_until[host] = (time.monotonic()
                                           + self.BULK_RETRY_SECONDS)


class PointToPointGroup:
    """Distributed coordination for one group: the main idx (0) hosts the
    lock state; lock/barrier/notify ride PTP messages
    (reference PointToPointBroker.h:26-97)."""

    def __init__(self, broker: PointToPointBroker, app_id: int,
                 group_id: int, group_size: int) -> None:
        self.broker = broker
        self.app_id = app_id
        self.group_id = group_id
        self.group_size = group_size

        self._mx = threading.RLock()
        self._local_mx = threading.Lock()
        self._lock_owner_idx = NO_LOCK_OWNER_IDX
        self._recursive_owners: list[int] = []
        # Waiters remember whether they asked for a recursive acquisition,
        # so a grant restores the right ownership structure
        self._lock_waiters: list[tuple[int, bool]] = []
        self._local_barrier: Optional[threading.Barrier] = None

    # ------------------------------------------------------------------
    @staticmethod
    def add_group_if_not_exists(broker: PointToPointBroker, app_id: int,
                                group_id: int, group_size: int) -> "PointToPointGroup":
        with broker._lock:
            group = broker._groups.get(group_id)
            if group is None:
                group = PointToPointGroup(broker, app_id, group_id, group_size)
                broker._groups[group_id] = group
            else:
                group.group_size = group_size
            return group

    # ------------------------------------------------------------------
    # Distributed lock
    # ------------------------------------------------------------------
    def lock(self, group_idx: int, recursive: bool = False) -> None:
        main_host = self.broker.get_host_for_receiver(
            self.group_id, POINT_TO_POINT_MAIN_IDX)

        if main_host == self.broker.host:
            acquired = False
            with self._mx:
                # Recursive and plain ownership exclude each other: a
                # recursive acquisition needs the plain lock free, and vice
                # versa.
                free_of_plain = self._lock_owner_idx == NO_LOCK_OWNER_IDX
                if recursive and free_of_plain and (
                        not self._recursive_owners
                        or self._recursive_owners[-1] == group_idx):
                    self._recursive_owners.append(group_idx)
                    acquired = True
                elif (not recursive and free_of_plain
                        and not self._recursive_owners):
                    self._lock_owner_idx = group_idx
                    acquired = True
                if not acquired:
                    self._lock_waiters.append((group_idx, recursive))

            locker_host = self.broker.get_host_for_receiver(
                self.group_id, group_idx)
            locker_is_local = locker_host == self.broker.host
            if acquired:
                if not locker_is_local:
                    self._notify_locked(group_idx)
                return
            if locker_is_local:
                # Queued: wait for the grant message from main
                self.broker.recv_message(self.group_id,
                                         POINT_TO_POINT_MAIN_IDX, group_idx,
                                         channel=COORD_CHANNEL)
            # A remote queued locker is notified by unlock() later
        else:
            # Ask the main host, then wait for the grant
            self.broker._get_client(main_host).group_lock(
                self.app_id, self.group_id, group_idx, recursive)
            self.broker.recv_message(self.group_id,
                                     POINT_TO_POINT_MAIN_IDX, group_idx,
                                     channel=COORD_CHANNEL)

    def unlock(self, group_idx: int, recursive: bool = False) -> None:
        main_host = self.broker.get_host_for_receiver(
            self.group_id, POINT_TO_POINT_MAIN_IDX)

        if main_host != self.broker.host:
            self.broker._get_client(main_host).group_unlock(
                self.app_id, self.group_id, group_idx, recursive)
            return

        with self._mx:
            if recursive:
                if self._recursive_owners:
                    self._recursive_owners.pop()
                if self._recursive_owners:
                    return
            else:
                self._lock_owner_idx = NO_LOCK_OWNER_IDX
            if self._lock_waiters:
                nxt, nxt_recursive = self._lock_waiters.pop(0)
                if nxt_recursive:
                    self._recursive_owners.append(nxt)
                else:
                    self._lock_owner_idx = nxt
                self._grant(nxt)

    def _grant(self, group_idx: int) -> None:
        self._notify_locked(group_idx)

    def _notify_locked(self, group_idx: int) -> None:
        self.broker.send_message(self.group_id, POINT_TO_POINT_MAIN_IDX,
                                 group_idx, b"\x00", channel=COORD_CHANNEL)

    def get_lock_owner(self, recursive: bool = False) -> int:
        with self._mx:
            if recursive:
                return (self._recursive_owners[-1]
                        if self._recursive_owners else NO_LOCK_OWNER_IDX)
            return self._lock_owner_idx

    def local_lock(self) -> None:
        self._local_mx.acquire()

    def local_try_lock(self) -> bool:
        return self._local_mx.acquire(blocking=False)

    def local_unlock(self) -> None:
        self._local_mx.release()

    # ------------------------------------------------------------------
    # Barrier / notify
    # ------------------------------------------------------------------
    def is_single_host(self) -> bool:
        idxs = self.broker.get_idxs_registered_for_host(self.group_id,
                                                        self.broker.host)
        return len(idxs) == self.group_size

    def barrier(self, group_idx: int) -> None:
        # Single-host fast path (reference uses a std::barrier)
        if self.is_single_host():
            with self._mx:
                if (self._local_barrier is None
                        or self._local_barrier.parties != self.group_size):
                    self._local_barrier = threading.Barrier(self.group_size)
            self._local_barrier.wait()
            return

        if group_idx == POINT_TO_POINT_MAIN_IDX:
            for i in range(1, self.group_size):
                self.broker.recv_message(self.group_id, i,
                                         POINT_TO_POINT_MAIN_IDX,
                                         channel=COORD_CHANNEL)
            for i in range(1, self.group_size):
                self.broker.send_message(self.group_id,
                                         POINT_TO_POINT_MAIN_IDX, i, b"\x00",
                                         channel=COORD_CHANNEL)
        else:
            self.broker.send_message(self.group_id, group_idx,
                                     POINT_TO_POINT_MAIN_IDX, b"\x00",
                                     channel=COORD_CHANNEL)
            self.broker.recv_message(self.group_id, POINT_TO_POINT_MAIN_IDX,
                                     group_idx, channel=COORD_CHANNEL)

    def notify(self, group_idx: int) -> None:
        """Non-main idxs signal the main, which collects all of them
        (reference PointToPointBroker.cpp:348-365)."""
        if group_idx == POINT_TO_POINT_MAIN_IDX:
            for i in range(1, self.group_size):
                self.broker.recv_message(self.group_id, i,
                                         POINT_TO_POINT_MAIN_IDX,
                                         channel=COORD_CHANNEL)
        else:
            self.broker.send_message(self.group_id, group_idx,
                                     POINT_TO_POINT_MAIN_IDX, b"\x00",
                                     channel=COORD_CHANNEL)


def mappings_from_decision(decision: SchedulingDecision) -> PointToPointMappings:
    out = PointToPointMappings(app_id=decision.app_id,
                               group_id=decision.group_id)
    for i in range(decision.n_messages):
        out.mappings.append(PointToPointMapping(
            host=decision.hosts[i],
            message_id=decision.message_ids[i],
            app_idx=decision.app_idxs[i],
            group_idx=decision.group_idxs[i],
            mpi_port=decision.mpi_ports[i],
            device_ids=[decision.device_ids[i]]
            if decision.device_ids[i] >= 0 else [],
        ))
    return out
