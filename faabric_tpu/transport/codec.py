"""Adaptive wire-codec plane: per-link codec selection + delta streams.

ROADMAP item 1 (the PR 10 remainder): the data plane shipped every byte
raw, while the snapshot subsystem already proved XOR+zlib deltas move
256 MiB in ~15 ms and the EQuARX-style int8 fold-leg quantization cut
wire bytes 5/8. Iterative workloads (parameter broadcast, solver
sendrecv ping-pong) resend near-identical buffers to the same peer
every round — this module is the machinery that notices and stops
paying full price:

- ``WireCodecGovernor``: picks raw / delta / zlib per (link,
  payload-class) from the rolling performance-profile store's measured
  per-host GiB/s (ISSUE 12; big-frame evidence only, comm-matrix
  window as the unmeasured-link fallback) and a cheap sampled
  byte-entropy estimate, so slow cross-host links compress while
  shm/loopback stays raw. Decisions are re-evaluated
  per profile window and are carried IN THE FRAME HEADER (codec
  byte + epochs, transport/bulk.py ``_FRAME``), never inferred by the
  receiver. The leader-ring quant knob (mpi/quant.py) resolves through
  the same governor, so lossy int8 becomes one policy among several
  instead of a global env switch.
- ``SenderDeltaCache``: the sender-side bounded cache of last-sent
  payloads per (group, src, dst, channel) stream. A sampled XOR
  density probe picks the best epoch-tagged base (cyclic
  chunk-pipelined streams re-match the same chunk position every
  round via the rotation hint); frames with no good base ship full
  (optionally zlib'd when entropy says it pays) and establish a fresh
  base.
- ``ReceiverDeltaCache``: the mirror image — epoch-keyed decoded
  payloads per stream. A delta whose base epoch is missing, whose crc
  fails, or whose decode blows up returns None → the bulk server NACKs
  and the sender escapes to full frames with the SAME seq. Torn or
  missing bases can therefore never decode garbage and never stall the
  protocol: the ordered-recv path simply sees the healed full frame.

Wire codec ids (the ``codec`` byte in the bulk frame header):
``CODEC_RAW`` frames bypass this module entirely; ``CODEC_FULL``
carries the raw payload and establishes base ``self_epoch``;
``CODEC_DELTA`` is the snapshot XOR+zlib command stream
(util/delta.py) against ``base_epoch``, its decode becoming
``self_epoch``; ``CODEC_ZLIB`` is a whole-payload zlib full frame for
low-entropy payloads with no usable base.

Knobs: ``FAABRIC_WIRE_CODEC`` (``auto`` default; ``raw`` disables;
``delta``/``zlib`` force a codec for eligible bulk streams;
``quant`` allows lossy int8 on the leader ring; comma-combinable,
e.g. ``delta,quant``), ``FAABRIC_DELTA_CACHE_MB`` (per-side base-cache
budget, default 128), ``FAABRIC_WIRE_CODEC_MIN_GIBS`` (auto-mode link
speed above which compression never pays — an explicit OVERRIDE: when
unset the threshold is tuned per destination from the perf-profile
store's measured delta-path effective rate, falling back to 4 GiB/s
with no delta evidence; see ``WireCodecGovernor._threshold_gibs``).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import zlib

import numpy as np

from faabric_tpu.telemetry import flight_record, get_metrics, get_perf_store
from faabric_tpu.util.delta import (
    DeltaSettings,
    apply_delta,
    delta_is_xor_only,
    sampled_overlap_parts,
    serialize_delta_parts,
)
from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

# -- wire codec ids (bulk frame header `codec` byte) ---------------------
CODEC_RAW = 0
CODEC_FULL = 1   # raw payload; establishes base `self_epoch`
CODEC_DELTA = 2  # util/delta.py stream vs `base_epoch` → `self_epoch`
CODEC_ZLIB = 3   # whole-payload zlib full frame (low-entropy escape)

# Frame header flag bits
FLAG_CACHE = 1   # receiver stores the decoded payload as `self_epoch`
FLAG_ESCAPE = 2  # full frame sent to heal a NACK / reconnect / force

CODEC_LABELS = {CODEC_RAW: "raw", CODEC_FULL: "delta-full",
                CODEC_DELTA: "delta", CODEC_ZLIB: "zlib"}

# Streams below this never bother with the codec plane: the cache
# bookkeeping costs more than the wire for small frames, and the RPC
# plane carries most of them anyway.
CODEC_MIN_BYTES = 64 * 1024

# Delta encode parameters: page-granular XOR + zlib over the dirty
# command stream — the exact settings the snapshot push proved out.
DELTA_SETTINGS = DeltaSettings(page_size=4096, use_xor=True, zlib_level=1)
# A sampled-page identity fraction below this means "different data,
# not a mutated round" — ship full instead of paying a doomed encode.
OVERLAP_MIN = 0.35
PROBE_PAGES = 8
# A delta bigger than this fraction of the raw payload loses to full.
DELTA_MAX_RATIO = 0.75
# Sampled bits/byte above which zlib full frames never pay.
ZLIB_ENTROPY_MAX = 6.5
# Per-stream bounds: base epochs kept (cyclic chunk pipelines need one
# per chunk position) and the NACK-resend window of recent coded seqs.
MAX_BASES_PER_STREAM = 48
SENT_WINDOW = 16
# Auto-mode bandwidth evidence must come from size classes at least
# this big: compact steady-state delta frames' wall time is dispatch
# overhead and reads as a falsely slow link — accepting it would lock
# a fast link into compression on its own output (profile says slow →
# keep delta → only small frames → profile keeps saying slow). With
# only small-frame evidence the store reports None and the comm-matrix
# raw-rate fallback decides, which is biased the OPPOSITE way (raw
# bytes over wire time) and lets a genuinely fast link escape.
GOVERNOR_BW_EVIDENCE_BYTES = 256 * 1024

_metrics = get_metrics()
_CODEC_TX_FRAMES = {
    label: _metrics.counter(
        "faabric_codec_frames_total",
        "Coded bulk frames sent per wire codec", codec=label)
    for label in ("delta", "delta-full", "zlib")
}
_CODEC_SAVED = {
    label: _metrics.counter(
        "faabric_codec_bytes_saved_total",
        "Raw-minus-wire bytes saved per codec", codec=label)
    for label in ("delta", "zlib")
}
_CODEC_ESCAPES = {
    reason: _metrics.counter(
        "faabric_codec_escapes_total",
        "Full-frame escapes by reason", reason=reason)
    for reason in ("nack", "reconnect", "lost_payload", "crc",
                   "base_missing", "decode_error")
}
# Rolling double-buffer base reuse (ISSUE 12 satellite): rounds whose
# steady-state insert/apply copy was replaced by an O(dirty) in-place
# patch of the two-rounds-old buffer, and the flatten bytes avoided
_CODEC_BASE_REUSE = {
    side: _metrics.counter(
        "faabric_codec_base_reuse_total",
        "Rolling base-buffer reuses (flatten/apply copy avoided)",
        side=side)
    for side in ("send", "recv")
}
_CODEC_BASE_REUSE_BYTES = {
    side: _metrics.counter(
        "faabric_codec_base_reuse_bytes_total",
        "Payload bytes whose full copy the rolling bases avoided",
        side=side)
    for side in ("send", "recv")
}
_CODEC_VERDICT_FLIPS = _metrics.counter(
    "faabric_codec_verdict_flips_total",
    "Governor per-link codec verdict changes (flight-recorded)")


def count_escape(reason: str) -> None:
    c = _CODEC_ESCAPES.get(reason)
    if c is not None:
        c.inc()


def payload_entropy(arr: np.ndarray, sample: int = 4096) -> float:
    """Sampled byte entropy in bits/byte (0..8). Three strided probes
    instead of one prefix read: parameter buffers often carry a
    low-entropy header before high-entropy weights."""
    n = arr.size
    if n == 0:
        return 0.0
    if n <= sample:
        s = arr
    else:
        step = max(1, sample // 3)
        s = np.concatenate([arr[:step], arr[n // 2:n // 2 + step],
                            arr[n - step:]])
    counts = np.bincount(s, minlength=256)
    p = counts[counts > 0] / s.size
    return float(-(p * np.log2(p)).sum())


def _cache_budget_bytes() -> int:
    try:
        mb = int(os.environ.get("FAABRIC_DELTA_CACHE_MB", "128"))
    except ValueError:
        mb = 128
    return max(0, mb) << 20


def crc_of(buf) -> int:
    return zlib.crc32(buf) & 0xFFFFFFFF


def _flatten(parts: list, total: int) -> np.ndarray:
    """One private contiguous uint8 array from ordered segments."""
    if len(parts) == 1:
        return np.array(parts[0], dtype=np.uint8, copy=True)
    flat = np.empty(total, dtype=np.uint8)
    off = 0
    for p in parts:
        flat[off:off + p.size] = p
        off += p.size
    return flat


class CodedFrame:
    """One encoded frame, ready for the bulk header + wire."""

    __slots__ = ("codec", "flags", "base_epoch", "self_epoch", "crc",
                 "wire", "raw_nbytes")

    def __init__(self, codec: int, flags: int, base_epoch: int,
                 self_epoch: int, crc: int, wire: np.ndarray,
                 raw_nbytes: int) -> None:
        self.codec = codec
        self.flags = flags
        self.base_epoch = base_epoch
        self.self_epoch = self_epoch
        self.crc = crc
        self.wire = wire
        self.raw_nbytes = raw_nbytes


class _SendStream:
    """Sender-side state for one (group, src, dst, channel) stream."""

    __slots__ = ("bases", "order", "sent", "hint", "next_epoch",
                 "force_full", "by_print", "roll", "last_delta", "hist")

    def __init__(self) -> None:
        self.bases: dict[int, np.ndarray] = {}   # epoch → payload copy
        self.order: list[int] = []               # insertion order
        self.sent: dict[int, int] = {}           # recent seq → epoch
        self.hint = 0                            # cyclic base rotation
        self.next_epoch = 1
        self.force_full = False
        # Content fingerprint → epoch (latest wins): O(1) base lookup
        # for sharded streams — a linear candidate scan degrades as
        # mutated shards append fresh epochs and the rotation hint
        # desyncs (measured: per-round cost grew ~25 ms/round at 13
        # shards). A probe still CONFIRMS every hit before use.
        self.by_print: dict[tuple, int] = {}
        # Rolling double-buffer lineage (ISSUE 12 satellite): the last
        # two consecutively-inserted epochs, plus the delta command
        # stream that transformed roll[0]'s content into roll[1]'s.
        # When round r encodes against roll[1], roll[0]'s buffer can be
        # patched in place (last_delta then this round's delta — both
        # O(dirty pages)) to hold round r's content, so the steady
        # state pays NO full flatten copy and NO allocation.
        self.roll: list[int] = []
        self.last_delta: bytes | None = None
        # Delta history for the NACK-heal window: (self_epoch,
        # base_epoch, delta_bytes) per delta insert, SENT_WINDOW deep.
        # Rolling recycles base BUFFERS, but same-size streams emit
        # pure-XOR deltas — which are self-inverting — so a recycled
        # epoch's payload is reconstructible by reverse-applying the
        # chain from any live base (see _reconstruct_locked). The
        # resend guarantee therefore survives the copy elimination.
        self.hist: list[tuple[int, int, bytes]] = []


# Fingerprint sample geometry: a few fixed 16-byte windows spread over
# the frame. A ~1% mutation usually misses every window, so unchanged
# shards hit their base in O(1); a window landing in the mutated slice
# just demotes that shard to the bounded scan.
_PRINT_OFFSETS = (0.13, 0.41, 0.67, 0.89)
_PRINT_BYTES = 16
# Fallback scan depth: cyclic streams should hit via fingerprint or
# hint; an unbounded scan over a mutating stream is O(rounds).
MAX_PROBE_CANDIDATES = 16


def _fingerprint(parts: list, total: int) -> tuple:
    """(total, sampled windows) over the logical frame, segment-aware."""
    samples = []
    bounds = []
    off = 0
    for p in parts:
        bounds.append((off, off + p.size, p))
        off += p.size
    for frac in _PRINT_OFFSETS:
        lo = min(int(total * frac), max(0, total - _PRINT_BYTES))
        hi = min(lo + _PRINT_BYTES, total)
        for s_lo, s_hi, p in bounds:
            if s_lo <= lo and hi <= s_hi:
                samples.append(p[lo - s_lo:hi - s_lo].tobytes())
                break
        else:
            samples.append(b"")  # straddles a segment boundary: skip
    return (total, *samples)


class SenderDeltaCache:
    """Bounded last-sent payload cache + delta encoder for one stripe.

    Sized by ``FAABRIC_DELTA_CACHE_MB``; eviction is global-LRU by
    insertion with per-stream ``MAX_BASES_PER_STREAM``. The NACK-resend
    window keeps the last ``SENT_WINDOW`` coded seqs' epochs alive so a
    receiver-reported undecodable frame can be re-shipped full with the
    SAME sequence number (the ordered-recv path then heals the gap).
    """

    # Concurrency contract (tools/concheck.py): every structure is
    # mutated under _lock. Callers additionally hold the owning
    # stripe's lock (lock order stripe.lock → _lock, see _Stripe):
    # encode and the NACK-heal resends must serialize against each
    # other so base/delta wire order matches cache order — _lock alone
    # guards the STRUCTURES, the stripe lock guards the PROTOCOL.
    GUARDS = {
        "_streams": "_lock",
        "_bytes": "_lock",
        "_lru": "_lock",
        "reused": "_lock",
        "reused_bytes": "_lock",
        "reconstructed": "_lock",
    }

    def __init__(self, budget_bytes: int | None = None) -> None:
        self._lock = threading.Lock()
        self._streams: dict[tuple, _SendStream] = {}
        # (key, epoch) → None, insertion-ordered: dict instead of list
        # so the per-frame rolled-path removal is O(1), not a scan of
        # every cached base under the lock
        self._lru: dict[tuple, None] = {}
        self._bytes = 0
        # Rolling base-reuse accounting (unit-pinned): rounds that
        # skipped the flatten copy, the payload bytes not copied, and
        # NACK heals served by XOR-chain reconstruction
        self.reused = 0
        self.reused_bytes = 0
        self.reconstructed = 0
        self.budget = (_cache_budget_bytes() if budget_bytes is None
                       else budget_bytes)

    # -- encode ---------------------------------------------------------
    def encode(self, key: tuple, parts: list, seq: int,
               mode: str = "delta") -> CodedFrame:
        """Encode one stream payload, given as ORDERED uint8 segments
        whose concatenation is the logical frame (a bulk frame arrives
        as [small MPI header | big body view] — the steady state must
        not pay a flatten copy). Always returns a frame — DELTA when a
        probed base matches (mode "delta"), FULL/ZLIB otherwise
        (establishing a fresh epoch-tagged base; the flatten copy a
        full frame pays IS the cache entry). Mode "zlib" skips base
        probing entirely."""
        total = sum(p.size for p in parts)
        with self._lock:
            st = self._streams.get(key)
            if st is None:
                st = self._streams[key] = _SendStream()
            if st.force_full:
                st.force_full = False
                return self._full_locked(key, st, parts, total, seq,
                                         True, FLAG_ESCAPE)
            if mode != "delta":
                return self._full_locked(key, st, parts, total, seq,
                                         True, 0)
            fp = _fingerprint(parts, total)
            base_epoch = self._pick_base_locked(st, parts, total, fp)
            if base_epoch == 0:
                return self._full_locked(key, st, parts, total, seq,
                                         True, 0)
            base = st.bases[base_epoch]
            delta = serialize_delta_parts(DELTA_SETTINGS, base, parts)
            if len(delta) >= total * DELTA_MAX_RATIO:
                return self._full_locked(key, st, parts, total, seq,
                                         True, 0)
            wire = np.frombuffer(delta, dtype=np.uint8)
            if len(delta) < 64 and total == base.nbytes:
                # Zero dirty pages: payload IS the base — reuse its
                # epoch, no cache copy, steady-state cost ≈ one memcmp
                self_epoch = base_epoch
            else:
                self_epoch = self._insert_rolled_locked(
                    key, st, parts, total, fp, base_epoch, delta)
            st.sent[seq] = self_epoch
            self._trim_sent_locked(st)
            _CODEC_TX_FRAMES["delta"].inc()
            _CODEC_SAVED["delta"].inc(total - len(delta))
            return CodedFrame(CODEC_DELTA, FLAG_CACHE, base_epoch,
                              self_epoch, crc_of(delta), wire, total)

    def _full_locked(self, key: tuple, st: _SendStream, parts: list,
                     total: int, seq: int, allow_zlib: bool,
                     flags: int) -> CodedFrame:
        flat = _flatten(parts, total)
        epoch = self._insert_locked(key, st, flat,
                                    _fingerprint([flat], total))
        # A full frame starts a fresh lineage (no delta transforms the
        # previous content into this one)
        st.roll = [epoch]
        st.last_delta = None
        st.sent[seq] = epoch
        self._trim_sent_locked(st)
        if allow_zlib and payload_entropy(flat) <= ZLIB_ENTROPY_MAX:
            z = zlib.compress(flat.tobytes(), 1)
            if len(z) < total * DELTA_MAX_RATIO:
                wire = np.frombuffer(z, dtype=np.uint8)
                _CODEC_TX_FRAMES["zlib"].inc()
                _CODEC_SAVED["zlib"].inc(total - len(z))
                return CodedFrame(CODEC_ZLIB, FLAG_CACHE | flags, 0,
                                  epoch, crc_of(z), wire, total)
        _CODEC_TX_FRAMES["delta-full"].inc()
        # The wire buffer IS the cache entry (read-only; the vectored
        # send only reads it) — a full frame costs exactly one copy
        return CodedFrame(CODEC_FULL, FLAG_CACHE | flags, 0, epoch, 0,
                          flat, total)

    def _insert_rolled_locked(self, key: tuple, st: _SendStream,
                              parts: list, total: int, fp: tuple,
                              base_epoch: int, delta: bytes) -> int:
        """Register the new payload as a base. Steady state — the frame
        was encoded against the LATEST base and the lineage's older
        buffer is idle — patches the two-rounds-old buffer in place:
        ``last_delta`` rolls it forward to the latest content, this
        round's delta to the new. Two O(dirty-pages) patches replace the
        O(total) flatten copy AND its allocation, with net-zero cache
        byte accounting. Every other shape (cyclic multi-base streams,
        resized payloads, a buffer still referenced by a NACK resend)
        falls back to the flatten path and restarts the lineage."""
        roll = st.roll
        if (len(roll) == 2 and base_epoch == roll[1]
                and st.last_delta is not None):
            buf = st.bases.get(roll[0])
            # refcount 3 == bases dict + `buf` + getrefcount's argument;
            # anything higher means an in-flight frame or NACK resend
            # still reads the buffer — never patch under a reader
            if (buf is not None and buf.nbytes == total
                    and sys.getrefcount(buf) <= 3):
                old = roll[0]
                try:
                    buf.flags.writeable = True
                    apply_delta(st.last_delta, buf, out=buf)
                    apply_delta(delta, buf, out=buf)
                except Exception:  # noqa: BLE001 — corrupt lineage:
                    # the half-patched buffer is garbage; drop it and
                    # restart the lineage on the flatten path below
                    self._drop_locked(key, st, old)
                    st.roll = []
                    st.last_delta = None
                else:
                    buf.flags.writeable = False
                    epoch = st.next_epoch
                    st.next_epoch += 1
                    # Re-register the same allocation under the new
                    # epoch: bookkeeping moves, byte accounting constant
                    del st.bases[old]
                    try:
                        st.order.remove(old)
                    except ValueError:
                        pass
                    self._lru.pop((key, old), None)
                    for k in [k for k, e in st.by_print.items()
                              if e == old]:
                        del st.by_print[k]
                    st.bases[epoch] = buf
                    st.order.append(epoch)
                    st.by_print[fp] = epoch
                    self._lru[(key, epoch)] = None
                    st.roll = [roll[1], epoch]
                    st.last_delta = bytes(delta)
                    self._hist_append_locked(st, epoch, base_epoch,
                                             st.last_delta)
                    self.reused += 1
                    self.reused_bytes += total
                    _CODEC_BASE_REUSE["send"].inc()
                    _CODEC_BASE_REUSE_BYTES["send"].inc(total)
                    return epoch
        epoch = self._insert_locked(key, st, _flatten(parts, total), fp)
        # Lineage (re)starts here: valid iff the base we encoded
        # against survived the insert's eviction pass
        st.roll = ([base_epoch, epoch] if base_epoch in st.bases
                   else [epoch])
        st.last_delta = bytes(delta)
        self._hist_append_locked(st, epoch, base_epoch, st.last_delta)
        return epoch

    @staticmethod
    def _hist_append_locked(st: _SendStream, self_epoch: int,
                            base_epoch: int, delta: bytes) -> None:
        st.hist.append((self_epoch, base_epoch, delta))
        while len(st.hist) > SENT_WINDOW:
            st.hist.pop(0)

    def _pick_base_locked(self, st: _SendStream, parts: list,
                          total: int, fp: tuple) -> int:
        """Best cached base epoch, or 0. Order of attack: the content
        fingerprint (O(1), unchanged shards), then the cyclic rotation
        hint, then a BOUNDED newest-first scan — every hit is confirmed
        by the sampled-page probe before use."""
        order = st.order
        n = len(order)
        if n == 0:
            return 0
        hit = st.by_print.get(fp)
        if hit is not None:
            base = st.bases.get(hit)
            if base is not None and base.nbytes == total \
                    and sampled_overlap_parts(
                        base, parts, DELTA_SETTINGS.page_size,
                        PROBE_PAGES) >= OVERLAP_MIN:
                return hit
        for probe in range(min(n, MAX_PROBE_CANDIDATES)):
            epoch = order[(st.hint + probe) % n]
            base = st.bases[epoch]
            if base.nbytes != total:
                continue
            frac = sampled_overlap_parts(base, parts,
                                         DELTA_SETTINGS.page_size,
                                         PROBE_PAGES)
            if frac >= OVERLAP_MIN:
                st.hint = (st.hint + probe + 1) % n
                return epoch
        return 0

    def _insert_locked(self, key: tuple, st: _SendStream,
                       flat: np.ndarray, fp: tuple) -> int:
        """``flat`` must be a PRIVATE contiguous uint8 array — it
        becomes the immutable cache entry without another copy."""
        epoch = st.next_epoch
        st.next_epoch += 1
        flat.flags.writeable = False
        st.bases[epoch] = flat
        st.order.append(epoch)
        st.by_print[fp] = epoch  # latest content under this print wins
        self._lru[(key, epoch)] = None
        self._bytes += flat.nbytes
        while len(st.order) > MAX_BASES_PER_STREAM:
            self._drop_locked(key, st, st.order[0])
        self._evict_locked()
        return epoch

    def _drop_locked(self, key: tuple, st: _SendStream,
                     epoch: int) -> None:
        # LRU entry goes first, unconditionally: an entry surviving an
        # early return here would wedge _evict_locked's head-pop loop
        self._lru.pop((key, epoch), None)
        base = st.bases.pop(epoch, None)
        if base is None:
            return
        self._bytes -= base.nbytes
        try:
            st.order.remove(epoch)
        except ValueError:
            pass
        for k in [k for k, e in st.by_print.items() if e == epoch]:
            del st.by_print[k]
        if epoch in st.roll:  # evicted lineage member: lineage is dead
            st.roll = []
            st.last_delta = None

    def _evict_locked(self) -> None:
        while self._bytes > self.budget and self._lru:
            key, epoch = next(iter(self._lru))
            st = self._streams.get(key)
            if st is None:
                self._lru.pop((key, epoch), None)
                continue
            self._drop_locked(key, st, epoch)

    def _trim_sent_locked(self, st: _SendStream) -> None:
        while len(st.sent) > SENT_WINDOW:
            st.sent.pop(next(iter(st.sent)))

    # -- NACK healing ---------------------------------------------------
    def take_for_resend(self, key: tuple, seq: int
                        ) -> tuple[np.ndarray, int] | None:
        """The raw payload + epoch for a NACKed seq (None if the resend
        window no longer covers it — the documented unhealable-gap
        corner, same stance as a bulk RST). An epoch whose BUFFER the
        rolling double-buffer recycled is reconstructed from the
        retained XOR delta chain (pure-XOR deltas are self-inverting),
        so base reuse does not narrow the heal window. Marks the stream
        so its next regular frame ships full, re-establishing a base
        the receiver certainly has."""
        with self._lock:
            st = self._streams.get(key)
            if st is None:
                return None
            st.force_full = True
            epoch = st.sent.get(seq)
            if epoch is None:
                return None
            base = st.bases.get(epoch)
            if base is None:
                return self._reconstruct_locked(st, epoch)
            return base, epoch

    def _reconstruct_locked(self, st: _SendStream, epoch: int
                            ) -> tuple[np.ndarray, int] | None:
        """Rebuild a recycled epoch's payload by reverse-applying the
        delta chain from the newest LIVE base down to ``epoch``: each
        hist entry's delta transformed base→self, and a pure-XOR delta
        applied to the SELF content yields the BASE content back.
        Overwrite commands (frame growth) are not invertible — a chain
        containing one gives up (the pre-existing lost_payload corner).
        O(total) copy + O(chain × dirty) patches, on the rare NACK path
        only."""
        # Walk hist newest-first until we reach the requested epoch,
        # requiring an unbroken base←self lineage
        chain: list[bytes] = []
        need = None  # the self_epoch the next-older entry must provide
        start = None  # the live epoch reconstruction starts from
        for self_e, base_e, delta in reversed(st.hist):
            if need is None:
                if st.bases.get(self_e) is None:
                    continue  # not live: keep looking for an anchor
                need = self_e
                start = self_e
            if self_e != need:
                return None  # lineage gap
            chain.append(delta)
            need = base_e
            if base_e == epoch:
                break
        else:
            return None
        if start is None:
            return None
        buf = st.bases[start].copy()
        try:
            for delta in chain:
                if not delta_is_xor_only(delta):
                    return None
                apply_delta(delta, buf, out=buf)
        except Exception:  # noqa: BLE001 — size drift, corrupt stream
            return None
        buf.flags.writeable = False
        self.reconstructed += 1
        return buf, epoch

    def reset(self) -> None:
        """Forget everything (stripe reconnect: the receiver's per-conn
        cache died with the connection, so every base is stale)."""
        with self._lock:
            self._streams.clear()
            self._lru.clear()  # dict: clears in O(n), no scans after
            self._bytes = 0

    # -- observability --------------------------------------------------
    @property
    def cached_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def stream_count(self) -> int:
        with self._lock:
            return len(self._streams)


class _RecvStream:
    __slots__ = ("bases", "order", "roll", "last_delta")

    def __init__(self) -> None:
        self.bases: dict[int, np.ndarray] = {}
        self.order: list[int] = []
        # Rolling lineage, mirror of the sender's (see _SendStream)
        self.roll: list[int] = []
        self.last_delta: bytes | None = None


class ReceiverDeltaCache:
    """Receiver-side epoch-keyed base cache (one per bulk connection —
    it dies with the conn, which is exactly when the sender resets its
    side). ``decode`` returns the raw payload array, or None when the
    frame cannot be decoded safely (caller NACKs)."""

    GUARDS = {
        "_streams": "_lock",
        "_bytes": "_lock",
        "_lru": "_lock",
    }

    def __init__(self, budget_bytes: int | None = None) -> None:
        self._lock = threading.Lock()
        self._streams: dict[tuple, _RecvStream] = {}
        self._lru: dict[tuple, None] = {}  # (key, epoch), insert order
        self._bytes = 0
        self.budget = (_cache_budget_bytes() if budget_bytes is None
                       else budget_bytes)

    def decode(self, key: tuple, codec: int, flags: int, base_epoch: int,
               self_epoch: int, crc: int, wire: np.ndarray,
               raw_nbytes: int) -> np.ndarray | None:
        """Decoded payload, or None (caller NACKs). Delivery is
        ZERO-COPY: the returned array is (or aliases) the immutable
        cache entry, marked read-only — the MPI layer already treats
        non-writable arrays as shared (copy-on-need), and a reader like
        the broadcast assembly pays nothing."""
        if codec == CODEC_FULL:
            if flags & FLAG_CACHE:
                self._store(key, self_epoch, wire)
            return wire
        if codec == CODEC_ZLIB:
            if crc_of(wire) != crc:
                count_escape("crc")
                return None
            try:
                raw = np.frombuffer(
                    zlib.decompress(wire.tobytes()), dtype=np.uint8)
            except zlib.error:
                count_escape("decode_error")
                return None
            if raw.size != raw_nbytes:
                count_escape("decode_error")
                return None
            if flags & FLAG_CACHE:
                self._store(key, self_epoch, raw)
            return raw
        if codec == CODEC_DELTA:
            if crc_of(wire) != crc:
                count_escape("crc")
                return None
            with self._lock:
                st = self._streams.get(key)
                base = st.bases.get(base_epoch) if st is not None else None
            if base is None:
                count_escape("base_missing")
                return None
            if self_epoch == base_epoch:
                # Identical payload: the cached base IS the message —
                # deliver it read-only, zero copies on either side
                return base
            delta_bytes = wire.tobytes()
            rolled = self._decode_rolled(key, base_epoch, self_epoch,
                                         delta_bytes, raw_nbytes)
            if rolled is not None:
                return rolled
            try:
                out = apply_delta(delta_bytes, base)
            except Exception:  # noqa: BLE001 — any decode blowup → NACK
                count_escape("decode_error")
                return None
            if out.size != raw_nbytes:
                count_escape("decode_error")
                return None
            self._store(key, self_epoch, out, lineage_base=base_epoch,
                        delta=delta_bytes)
            return out
        count_escape("decode_error")
        return None

    def _decode_rolled(self, key: tuple, base_epoch: int, self_epoch: int,
                       delta: bytes, raw_nbytes: int) -> np.ndarray | None:
        """Steady-state delta decode without the per-round apply copy:
        when the frame extends the stream's rolling lineage and the
        two-rounds-old buffer has no outside reader (delivered arrays
        are shared zero-copy with the MPI layer — the refcount check
        proves every consumer dropped its reference), patch that buffer
        in place (two O(dirty) passes) instead of allocating a fresh
        full-size base copy. None → caller takes the allocating path."""
        with self._lock:
            st = self._streams.get(key)
            if (st is None or len(st.roll) != 2
                    or base_epoch != st.roll[1]
                    or st.last_delta is None
                    or self_epoch in st.bases):
                return None
            buf = st.bases.get(st.roll[0])
            # bases dict + `buf` + getrefcount's argument = 3; a live
            # consumer (or the ordered-recv queue) holding the array it
            # was delivered pushes the count higher and vetoes reuse
            if (buf is None or buf.nbytes != raw_nbytes
                    or sys.getrefcount(buf) > 3):
                return None
            old = st.roll[0]
            try:
                # May refuse on a buffer backed by an immutable object
                # (e.g. a frombuffer view of bytes) — that's a veto, not
                # an error; the allocating path below handles the frame
                buf.flags.writeable = True
                apply_delta(st.last_delta, buf, out=buf)
                apply_delta(delta, buf, out=buf)
            except Exception:  # noqa: BLE001 — half-patched buffer is
                # garbage: drop it, kill the lineage, decode normally
                self._drop_locked(key, st, old)
                st.roll = []
                st.last_delta = None
                return None
            buf.flags.writeable = False
            del st.bases[old]
            try:
                st.order.remove(old)
            except ValueError:
                pass
            self._lru.pop((key, old), None)
            st.bases[self_epoch] = buf
            st.order.append(self_epoch)
            self._lru[(key, self_epoch)] = None
            st.roll = [base_epoch, self_epoch]
            st.last_delta = delta
            _CODEC_BASE_REUSE["recv"].inc()
            _CODEC_BASE_REUSE_BYTES["recv"].inc(raw_nbytes)
            return buf

    def _store(self, key: tuple, epoch: int, payload: np.ndarray,
               lineage_base: int | None = None,
               delta: bytes | None = None) -> None:
        """Adopt ``payload`` as the immutable base for ``epoch`` — no
        copy: the caller hands over a buffer it exclusively owns (recv
        buffer, decompress output, apply_delta result) and delivery
        shares it read-only. ``lineage_base``/``delta`` extend the
        rolling lineage when this store resulted from a delta against
        the lineage head (see _decode_rolled)."""
        copy = payload
        try:
            copy.flags.writeable = False
        except ValueError:
            copy = payload.copy()
            copy.flags.writeable = False
        with self._lock:
            st = self._streams.get(key)
            if st is None:
                st = self._streams[key] = _RecvStream()
            if epoch in st.bases:
                return  # duplicate-seq redelivery: identical content
            if (lineage_base is not None and delta is not None
                    and lineage_base in st.bases):
                st.roll = [lineage_base, epoch]
                st.last_delta = delta
            else:
                st.roll = [epoch]
                st.last_delta = None
            st.bases[epoch] = copy
            st.order.append(epoch)
            self._lru[(key, epoch)] = None
            self._bytes += copy.nbytes
            while len(st.order) > MAX_BASES_PER_STREAM:
                self._drop_locked(key, st, st.order[0])
            while self._bytes > self.budget and self._lru:
                k, e = next(iter(self._lru))
                s = self._streams.get(k)
                if s is None:
                    self._lru.pop((k, e), None)
                    continue
                self._drop_locked(k, s, e)

    def _drop_locked(self, key: tuple, st: _RecvStream,
                     epoch: int) -> None:
        # LRU entry first, unconditionally — a surviving entry would
        # wedge the budget-eviction head-pop loop above
        self._lru.pop((key, epoch), None)
        base = st.bases.pop(epoch, None)
        if base is None:
            return
        self._bytes -= base.nbytes
        try:
            st.order.remove(epoch)
        except ValueError:
            pass
        if epoch in st.roll:  # evicted lineage member: lineage is dead
            st.roll = []
            st.last_delta = None

    def drop_bases(self) -> None:
        """Test/ops hook: forget every base (simulates a migration remap
        landing the stream on a receiver with stale epoch state)."""
        with self._lock:
            self._streams.clear()
            self._lru.clear()
            self._bytes = 0


# ---------------------------------------------------------------------------
# Governor
# ---------------------------------------------------------------------------

_VALID_TOKENS = {"auto", "raw", "off", "delta", "zlib", "quant"}


def _parse_mode(spec: str) -> frozenset:
    tokens = {t.strip().lower() for t in spec.split(",") if t.strip()}
    bad = tokens - _VALID_TOKENS
    if bad:
        logger.warning("Ignoring unknown FAABRIC_WIRE_CODEC token(s) %s",
                       sorted(bad))
        tokens -= bad
    if not tokens:
        tokens = {"auto"}
    return frozenset(tokens)


class WireCodecGovernor:
    """Per-link codec selection, deterministic on both ends because the
    verdict rides the bulk frame header (and the NaN-scale sentinel on
    the quant plane) — the receiver decodes what the header says, never
    what it guesses the sender chose.

    Policy (``auto``): shm-capable / same-machine links stay raw —
    a ring memcpy beats any codec. Cross-machine links compress when
    their measured bandwidth — the rolling profile store's big-frame
    evidence first (which persists across restarts), the comm-matrix
    window as fallback — is below
    ``FAABRIC_WIRE_CODEC_MIN_GIBS`` (or unmeasured: a fresh WAN link is
    assumed slow until a measurement says otherwise). Forced tokens
    (``delta``/``zlib``) override locality so tests and benches can
    exercise the codec plane on loopback; ``raw``/``off`` disables it.
    Decisions are cached per (host, link, size-class) and re-evaluated
    every comm-matrix window."""

    # Concurrency contract: the decision cache is read/written from
    # every sending thread; the mode/threshold fields are set once in
    # __init__ (or under _lock by set_mode) and read lock-free as
    # immutable snapshots.
    GUARDS = {
        "_decisions": "_lock",
        "_matrix_cells": "_lock",
        "_matrix_expires": "_lock",
    }

    WINDOW_SECONDS = 5.0

    # Clamp range for the TUNED threshold: measurement glitches must
    # not push the break-even outside physically sensible link speeds
    TUNED_MIN_GIBS = 0.25
    TUNED_MAX_GIBS = 32.0

    def __init__(self, mode: str | None = None) -> None:
        self._lock = threading.Lock()
        if mode is None:
            mode = os.environ.get("FAABRIC_WIRE_CODEC", "auto")
        self.mode = _parse_mode(mode)
        # ISSUE 15 satellite (the ROADMAP item-1 leftover): the
        # auto-mode bandwidth threshold is TUNED from the perf-profile
        # store per destination (see _threshold_gibs) — the env knob is
        # now an OVERRIDE, applied only when explicitly set; 4.0 GiB/s
        # remains the no-evidence default.
        self.min_gibs_env_set = "FAABRIC_WIRE_CODEC_MIN_GIBS" in os.environ
        try:
            self.min_gibs = float(os.environ.get(
                "FAABRIC_WIRE_CODEC_MIN_GIBS", "4.0"))
        except ValueError:
            self.min_gibs = 4.0
            self.min_gibs_env_set = False
        self._decisions: dict[tuple, tuple[str, float]] = {}
        self._matrix_cells: list[dict] = []
        self._matrix_expires = 0.0

    def set_mode(self, spec: str) -> None:
        """Test/bench hook: replace the mode and drop cached verdicts."""
        with self._lock:
            self.mode = _parse_mode(spec)
            self._decisions.clear()

    # -- bulk-plane (lossless) selection --------------------------------
    def bulk_codec(self, host: str, local: bool, src, dst,
                   nbytes: int) -> str:
        """'delta' | 'zlib' | 'raw' for one bulk frame. ``local`` is the
        shm-capability verdict the BulkClient already computed (aliased
        same-machine peers count — their wire is a ring memcpy)."""
        mode = self.mode
        if "raw" in mode or "off" in mode:
            return "raw"
        if "delta" in mode:
            return "delta"
        if "zlib" in mode:
            return "zlib"
        # auto: locality first, then the measured link
        if local:
            return "raw"
        key = (host, src, dst, int(nbytes).bit_length())
        now = time.monotonic()
        with self._lock:
            hit = self._decisions.get(key)
            if hit is not None and now < hit[1]:
                return hit[0]
        # Primary signal (ISSUE 12, the PR 11 follow-up): the rolling
        # performance-profile store's decayed per-host bandwidth — which
        # also survives restarts via FAABRIC_PERF_PROFILE_DIR seeding.
        # Big-frame evidence only (see GOVERNOR_BW_EVIDENCE_BYTES); the
        # ad-hoc comm-matrix window remains as the fallback while the
        # store has no qualifying evidence for this destination.
        gibs = get_perf_store().link_gibs(
            host, plane="bulk-tcp",
            min_bytes=GOVERNOR_BW_EVIDENCE_BYTES)
        source = "profile"
        if gibs is None:
            gibs = self._link_gibs(src, dst)
            source = "commmatrix"
        threshold, threshold_src = self._threshold_gibs(host, src, dst)
        choice = "delta" if (gibs is None or gibs < threshold) \
            else "raw"
        with self._lock:
            prev = self._decisions.get(key)
            self._decisions[key] = (choice, now + self.WINDOW_SECONDS)
            if len(self._decisions) > 4096:
                self._decisions.clear()  # cardinality backstop
        if prev is None or prev[0] != choice:
            # Post-mortem breadcrumb (ISSUE 12 satellite): WHY a link
            # changed codec — bounded by the decision cardinality ×
            # actual verdict changes, so the ring never floods
            if prev is not None:
                _CODEC_VERDICT_FLIPS.inc()
            flight_record("codec_verdict", host=host, src=src, dst=dst,
                          verdict=choice,
                          prev=prev[0] if prev else None,
                          gibs=round(gibs, 3) if gibs is not None
                          else None, source=source,
                          threshold=round(threshold, 3),
                          threshold_src=threshold_src)
        return choice

    def _threshold_gibs(self, host: str, src, dst) -> tuple[float, str]:
        """The raw-vs-compress break-even bandwidth for one link
        (ISSUE 15 satellite, the ROADMAP item-1 leftover).

        Priority: an EXPLICITLY set ``FAABRIC_WIRE_CODEC_MIN_GIBS``
        always wins (the operator override). Otherwise the threshold is
        TUNED from measurement: compression pays exactly while the raw
        link is slower than the delta path's measured *effective*
        payload rate — the store's delta-codec wire bandwidth toward
        ``host`` × the link's observed raw/wire compression ratio (comm
        matrix ``bytes_raw``/``bytes`` on delta rows; per-(src, dst)
        first, any measured delta link as fallback). No delta evidence
        yet → the 4 GiB/s default, exactly as before."""
        if self.min_gibs_env_set:
            return self.min_gibs, "env"
        delta_gibs = get_perf_store().link_gibs(
            host, plane="bulk-tcp", codec="delta")
        if delta_gibs is None or delta_gibs <= 0:
            return self.min_gibs, "default"
        ratio = self._delta_ratio(src, dst)
        if ratio is None:
            return self.min_gibs, "default"
        tuned = min(max(delta_gibs * ratio, self.TUNED_MIN_GIBS),
                    self.TUNED_MAX_GIBS)
        return tuned, "tuned"

    def _delta_ratio(self, src, dst) -> float | None:
        """Observed raw/wire byte ratio of delta frames — per (src,
        dst) when that link has delta history, the matrix-wide delta
        aggregate otherwise (a fresh link borrows the workload's
        typical compressibility). Reuses the windowed comm-matrix
        snapshot ``_link_gibs`` maintains."""
        self._link_gibs(src, dst)  # refresh the window if due
        with self._lock:
            cells = self._matrix_cells
        link_raw = link_wire = all_raw = all_wire = 0
        for c in cells:
            if c.get("codec") != "delta" or c.get("plane") != "bulk-tcp":
                continue
            wire = c.get("bytes", 0)
            raw = c.get("bytes_raw", wire)
            all_raw += raw
            all_wire += wire
            if c.get("src") == str(src) and c.get("dst") == str(dst):
                link_raw += raw
                link_wire += wire
        if link_wire > 0:
            return link_raw / link_wire
        if all_wire > 0:
            return all_raw / all_wire
        return None

    def _link_gibs(self, src, dst) -> float | None:
        """Measured GiB/s for the (src, dst) bulk link from the comm
        matrix, refreshed once per window."""
        from faabric_tpu.telemetry import get_comm_matrix

        now = time.monotonic()
        with self._lock:
            if now >= self._matrix_expires:
                snap = get_comm_matrix().snapshot() or {}
                self._matrix_cells = snap.get("cells", [])
                self._matrix_expires = now + self.WINDOW_SECONDS
            cells = self._matrix_cells
        best = None
        for c in cells:
            if c.get("plane") != "bulk-tcp":
                continue
            if c.get("src") != str(src) or c.get("dst") != str(dst):
                continue
            lat = c.get("lat_sum") or 0.0
            if lat <= 0:
                continue
            gibs = (c.get("bytes_raw", c.get("bytes", 0)) / lat) / (1 << 30)
            if best is None or gibs > best:
                best = gibs
        return best

    # -- quant (lossy) policy for the MPI leader ring -------------------
    def quant_mode(self, world_knob: str) -> str:
        """The effective allreduce quant mode: the explicit world/env
        knob wins (back-compat: FAABRIC_ALLREDUCE_QUANT=int8 forces the
        int8 fold leg everywhere); otherwise the ``quant`` governor
        token allows it, per-link."""
        if world_knob:
            return world_knob
        return "int8" if "quant" in self.mode else ""

    def quant_for_link(self, world_knob: str, dst_host: str,
                       local: bool) -> bool:
        """Whether THIS leader-ring hop should quantize. The legacy
        knob quantizes every hop (the PR 10 contract). Governor-driven
        quant in AUTO mode skips same-machine hops — their bytes are
        nearly free, so lossy compression there is pure error for no
        bandwidth; forced modes (e.g. ``delta,quant`` in a bench)
        quantize every hop like the knob."""
        if world_knob:
            return True
        if "quant" not in self.mode:
            return False
        if "auto" in self.mode and local:
            return False
        return True


_governor: WireCodecGovernor | None = None
_governor_lock = threading.Lock()


def get_wire_governor() -> WireCodecGovernor:
    global _governor
    if _governor is None:
        with _governor_lock:
            if _governor is None:
                _governor = WireCodecGovernor()
    return _governor


def set_wire_codec(spec: str) -> None:
    """Process-wide override (tests / bench workers)."""
    get_wire_governor().set_mode(spec)


def reset_wire_governor() -> None:
    """Test hook: drop the singleton so the next use re-reads env."""
    global _governor
    with _governor_lock:
        _governor = None
