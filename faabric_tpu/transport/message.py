"""Wire framing.

Reference analog: include/faabric/transport/Message.h:11-23 — there a
16-byte header over nng_msg {u8 code, u64 size, i32 seqnum}; here a 24-byte
header over a TCP stream carrying a JSON control section and a raw binary
tail (so big payloads — snapshot contents, MPI buffers — never pass through
JSON):

    magic u16 | code u8 | resp u8 | seqnum i64 | json_len u32 | bin_len u64

SHUTDOWN uses header code 220 with a magic payload, as the reference does
(Message.h:22-23).
"""

from __future__ import annotations

import dataclasses
import enum
import json
import socket
import struct
from typing import Any

HEADER_FMT = "<HBBqIQ"
HEADER_LEN = struct.calcsize(HEADER_FMT)
MAGIC = 0xFAAB

SHUTDOWN_CODE = 220
SHUTDOWN_PAYLOAD = b"\x00\x00\x42\x99"

NO_SEQUENCE_NUM = -1

# Sanity bounds on incoming frames: a corrupt/hostile frame with valid magic
# must not trigger a multi-GB allocation. The JSON control section is small
# by design (bulk data rides the binary tail); the tail is bounded at 8 GiB
# (largest legitimate payloads are snapshot contents / MPI buffers).
MAX_JSON_LEN = 64 * 1024 * 1024
MAX_BIN_LEN = 8 * 1024 * 1024 * 1024


class MessageResponseCode(enum.IntEnum):
    SUCCESS = 0
    TERM = 1
    TIMEOUT = 2
    ERROR = 3


class TransportError(Exception):
    pass


class ConnectionClosed(TransportError):
    pass


@dataclasses.dataclass
class TransportMessage:
    code: int
    header: dict[str, Any] = dataclasses.field(default_factory=dict)
    payload: bytes = b""
    seqnum: int = NO_SEQUENCE_NUM
    response_code: int = int(MessageResponseCode.SUCCESS)

    def is_shutdown(self) -> bool:
        return self.code == SHUTDOWN_CODE and self.payload == SHUTDOWN_PAYLOAD

    @classmethod
    def shutdown(cls) -> "TransportMessage":
        return cls(code=SHUTDOWN_CODE, payload=SHUTDOWN_PAYLOAD)


def send_frame(sock: socket.socket, msg: TransportMessage) -> None:
    header_json = json.dumps(msg.header).encode() if msg.header else b""
    payload = msg.payload or b""
    head = struct.pack(
        HEADER_FMT,
        MAGIC,
        msg.code & 0xFF,
        msg.response_code & 0xFF,
        msg.seqnum,
        len(header_json),
        len(payload),
    )
    # One syscall for small messages; for large payloads sendall the tail
    # separately to avoid a copy of the payload bytes.
    if len(payload) <= 65536:
        sock.sendall(head + header_json + payload)
    else:
        sock.sendall(head + header_json)
        sock.sendall(payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    if n == 0:
        return b""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    try:
        while got < n:
            r = sock.recv_into(view[got:], n - got)
            if r == 0:
                raise ConnectionClosed("Socket closed mid-frame")
            got += r
    except (ConnectionClosed, OSError) as e:
        e.bytes_read = got  # type: ignore[attr-defined]
        raise
    return bytes(buf)


def recv_frame(sock: socket.socket) -> TransportMessage:
    try:
        head = _recv_exact(sock, HEADER_LEN)
    except (ConnectionClosed, OSError) as e:
        # Nothing of the response arrived: lets callers distinguish a stale
        # keep-alive connection (safe to retry the request on a fresh dial)
        # from a connection dropped mid-response.
        if getattr(e, "bytes_read", 1) == 0:
            e.no_response_data = True  # type: ignore[attr-defined]
        raise
    magic, code, resp, seqnum, json_len, bin_len = struct.unpack(HEADER_FMT, head)
    if magic != MAGIC:
        raise TransportError(f"Bad frame magic: {magic:#x}")
    if json_len > MAX_JSON_LEN or bin_len > MAX_BIN_LEN:
        from faabric_tpu.util.bytes import format_byte_size

        raise TransportError(
            f"Frame exceeds size bounds (json={format_byte_size(json_len)}, "
            f"bin={format_byte_size(bin_len)})"
        )
    header_json = _recv_exact(sock, json_len)
    payload = _recv_exact(sock, bin_len)
    header = json.loads(header_json) if header_json else {}
    return TransportMessage(
        code=code, header=header, payload=payload, seqnum=seqnum, response_code=resp
    )


def tune_socket(sock: socket.socket) -> None:
    """Data-plane socket tuning — the analog of the reference's OpenMPI-
    recommended options (transport/tcp/Socket.h:75-78): TCP_NODELAY + large
    send/recv buffers."""
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 16 * 1024 * 1024)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 16 * 1024 * 1024)
    except OSError:
        pass
