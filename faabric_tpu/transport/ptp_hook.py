"""Indirection between the planner and the PTP broker.

The planner distributes group mappings for every decision it takes
(reference Planner.cpp → PointToPointBroker::
setAndSendMappingsFromSchedulingDecision). The broker registers itself here
at import time; until then sending mappings is a no-op so the control plane
works stand-alone.
"""

from __future__ import annotations

from typing import Callable, Optional

from faabric_tpu.batch_scheduler.decision import SchedulingDecision

_sender: Optional[Callable[[SchedulingDecision], None]] = None


def register_mapping_sender(fn: Callable[[SchedulingDecision], None]) -> None:
    global _sender
    _sender = fn


def send_mappings_from_decision(decision: SchedulingDecision) -> None:
    if _sender is not None:
        _sender(decision)
