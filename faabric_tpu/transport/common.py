"""Transport constants and host resolution.

Port map mirrors the reference (include/faabric/transport/common.h:283-309):
state 8003/8004, function 8005/8006, snapshot 8007/8008, PTP 8009/8010,
planner 8011/8012, MPI data-plane base 8020.

Host aliasing supports the reference's "fake second host by IP aliasing"
test trick (SURVEY.md §4.2): in-process tests register an alias mapping a
fake host name to (127.0.0.1, port_offset) so two full per-host runtimes can
coexist in one process on distinct port ranges.
"""

from __future__ import annotations

import threading

STATE_ASYNC_PORT = 8003
STATE_SYNC_PORT = 8004
FUNCTION_CALL_ASYNC_PORT = 8005
FUNCTION_CALL_SYNC_PORT = 8006
SNAPSHOT_ASYNC_PORT = 8007
SNAPSHOT_SYNC_PORT = 8008
POINT_TO_POINT_ASYNC_PORT = 8009
POINT_TO_POINT_SYNC_PORT = 8010
PLANNER_ASYNC_PORT = 8011
PLANNER_SYNC_PORT = 8012

MPI_BASE_PORT = 8020
MPI_PORTS_PER_HOST = 512

DEFAULT_SOCKET_TIMEOUT = 60.0

_aliases: dict[str, tuple[str, int]] = {}
_alias_lock = threading.Lock()
_env_aliases_loaded = False


def register_host_alias(host: str, ip: str = "127.0.0.1", port_offset: int = 0) -> None:
    with _alias_lock:
        _aliases[host] = (ip, port_offset)


def _load_env_aliases_locked() -> None:
    """Multi-process single-machine clusters share one alias table via
    FAABRIC_HOST_ALIASES="w1=127.0.0.1+30000,w2=127.0.0.1+31000" — the
    analog of the reference's docker-compose network hostnames."""
    global _env_aliases_loaded
    if _env_aliases_loaded:
        return
    _env_aliases_loaded = True
    import os

    spec = os.environ.get("FAABRIC_HOST_ALIASES", "")
    for entry in filter(None, (e.strip() for e in spec.split(","))):
        try:
            name, target = entry.split("=", 1)
            ip, _, offset = target.partition("+")
            _aliases.setdefault(name, (ip or "127.0.0.1", int(offset or 0)))
        except ValueError:
            continue


def resolve_host(host: str, port: int) -> tuple[str, int]:
    """Map a logical host + canonical port to a dialable (ip, port)."""
    with _alias_lock:
        _load_env_aliases_locked()
        if host in _aliases:
            ip, offset = _aliases[host]
            return ip, port + offset
    return host, port


def host_is_local(host: str) -> bool:
    """Whether a logical host resolves to THIS machine (loopback or
    the primary interface address) — the link class the shm fast paths
    key on and the wire-codec governor keeps raw (ISSUE 11): a
    same-machine "wire" is a memcpy, so compressing it is pure CPU for
    no bandwidth."""
    from faabric_tpu.util.network import is_local_ip

    ip, _ = resolve_host(host, 0)
    return is_local_ip(ip)


def get_host_alias_offset(host: str) -> int:
    with _alias_lock:
        _load_env_aliases_locked()
        if host in _aliases:
            return _aliases[host][1]
    return 0


def clear_host_aliases() -> None:
    global _env_aliases_loaded
    with _alias_lock:
        _aliases.clear()
        _env_aliases_loaded = False
