"""RPC server base — the MessageEndpointServer analog
(include/faabric/transport/MessageEndpointServer.h:43-83,
src/transport/MessageEndpointServer.cpp:29-202).

Two listening ports per server: an async plane (fire-and-forget push) and a
sync plane (request/response). The reference fans one nng socket out to N
worker threads via contexts; here each accepted connection gets a reader
thread which dispatches frames onto a shared work queue consumed by N
workers — same effect (serialised accept, parallel handling), idiomatic for
Python sockets.

Graceful stop: a shutdown frame (code 220 + magic payload) per worker, as in
the reference. ``set_request_latch``/``await_request_latch`` synchronise
tests with server-side processing (MessageEndpointServer.h:57-59).
"""

from __future__ import annotations

import errno
import socket
import threading
import time
from typing import Any

from faabric_tpu.telemetry import (
    NULL_METRIC,
    NULL_SPAN,
    get_metrics,
    span_from_remote,
    tracing_enabled,
)
from faabric_tpu.transport.message import (
    ConnectionClosed,
    MessageResponseCode,
    TransportError,
    TransportMessage,
    recv_frame,
    send_frame,
)
from faabric_tpu.util.latch import Latch
from faabric_tpu.util.logging import get_logger
from faabric_tpu.util.queues import Queue

logger = get_logger(__name__)

_metrics = get_metrics()
_RX_FRAMES = {
    plane: _metrics.counter(
        "faabric_transport_rx_frames_total",
        "Frames received on the shared RPC plane", plane=plane)
    for plane in ("async", "sync")
}
_RX_BYTES = {
    plane: _metrics.counter(
        "faabric_transport_rx_bytes_total",
        "Payload bytes received on the shared RPC plane", plane=plane)
    for plane in ("async", "sync")
}
_TX_FRAMES = _metrics.counter(
    "faabric_transport_tx_frames_total",
    "Frames sent on the shared RPC plane", plane="sync-response")
_TX_BYTES = _metrics.counter(
    "faabric_transport_tx_bytes_total",
    "Payload bytes sent on the shared RPC plane", plane="sync-response")
_HANDLE_SECONDS = {
    plane: _metrics.histogram(
        "faabric_transport_handle_seconds",
        "Server-side request handling latency", plane=plane)
    for plane in ("async", "sync")
}
_QUEUE_DEPTH = _metrics.gauge(
    "faabric_transport_work_queue_depth",
    "Async-plane frames queued awaiting a worker thread")


class MessageEndpointServer:
    # Concurrency contract (tools/concheck.py): the connection set and
    # per-connection reader threads are shared between the accept loops
    # and stop(); the test latch is armed/fired across threads.
    # Deliberately unlisted: _threads (the fixed worker/accept pool) and
    # _running are start/stop sequenced, the listeners are write-once at
    # start, and _work is an internally-synchronized queue.
    GUARDS = {
        "_conns": "_conn_lock",
        "_conn_threads": "_conn_lock",
        "_request_latch": "_latch_lock",
    }

    def __init__(
        self,
        async_port: int,
        sync_port: int,
        label: str = "",
        n_threads: int = 2,
        bind_host: str = "0.0.0.0",
    ) -> None:
        self.async_port = async_port
        self.sync_port = sync_port
        self.label = label or self.__class__.__name__
        self.n_threads = max(1, n_threads)
        self.bind_host = bind_host

        self._async_listener: socket.socket | None = None
        self._sync_listener: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._conn_threads: list[threading.Thread] = []
        self._conns: set[socket.socket] = set()
        self._conn_lock = threading.Lock()
        self._running = False
        self._work: Queue[tuple[TransportMessage, socket.socket | None]] = Queue()
        self._request_latch: Latch | None = None
        self._latch_lock = threading.Lock()

    # ------------------------------------------------------------------
    # Virtual handlers
    # ------------------------------------------------------------------
    def do_async_recv(self, msg: TransportMessage) -> None:
        raise NotImplementedError

    def do_sync_recv(self, msg: TransportMessage) -> TransportMessage:
        raise NotImplementedError

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------
    def start(self) -> None:
        if self._running:
            return
        self._running = True
        try:
            self._async_listener = self._listen(self.async_port)
            self._sync_listener = self._listen(self.sync_port)
        except OSError:
            # A half-started server must not leak its first listener: a
            # bind failure on the sync port would otherwise leave the
            # async port held by a server nobody tracks, poisoning the
            # port range for every later bind (the EADDRINUSE cascade).
            self._running = False
            for listener in (self._async_listener, self._sync_listener):
                if listener is not None:
                    try:
                        listener.close()
                    except OSError:
                        pass
            self._async_listener = self._sync_listener = None
            raise
        for listener, plane in ((self._async_listener, "async"), (self._sync_listener, "sync")):
            t = threading.Thread(
                target=self._accept_loop, args=(listener, plane),
                name=f"transport/accept@{self.label}-{plane}", daemon=True,
            )
            t.start()
            self._threads.append(t)
        for i in range(self.n_threads):
            t = threading.Thread(
                target=self._worker_loop, name=f"transport/worker@{self.label}-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)
        logger.debug(
            "%s started (async=%d sync=%d threads=%d)",
            self.label, self.async_port, self.sync_port, self.n_threads,
        )

    def stop(self) -> None:
        if not self._running:
            return
        self._running = False
        for _ in range(self.n_threads):
            self._work.enqueue((TransportMessage.shutdown(), None))
        for listener in (self._async_listener, self._sync_listener):
            if listener is not None:
                # shutdown() is required to wake threads blocked in accept();
                # close() alone leaves the file description (and the bound
                # port) alive until the accept returns.
                try:
                    listener.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    listener.close()
                except OSError:
                    pass
        # Wake connection readers blocked in recv_frame: shut their sockets
        # down so they fail fast instead of holding the connection until the
        # client's timeout.
        with self._conn_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2.0)
        # Snapshot under the lock: the accept loop appends conn threads
        # concurrently until its listener wakeup lands (concheck)
        with self._conn_lock:
            conn_threads, self._conn_threads = self._conn_threads, []
        for t in conn_threads:
            t.join(timeout=2.0)
        self._threads.clear()
        with self._conn_lock:
            self._conns.clear()
        logger.debug("%s stopped", self.label)

    # ------------------------------------------------------------------
    # Test synchronisation
    # ------------------------------------------------------------------
    def set_request_latch(self) -> None:
        with self._latch_lock:
            self._request_latch = Latch.create(2)

    def await_request_latch(self) -> None:
        with self._latch_lock:
            latch = self._request_latch
        if latch is not None:
            latch.wait()
            with self._latch_lock:
                # Only clear the latch we waited on: a test re-arming
                # between the wait and this clear must keep ITS latch
                # (check-then-act — the concheck lint's canonical case)
                if self._request_latch is latch:
                    self._request_latch = None

    def _fire_request_latch(self) -> None:
        with self._latch_lock:
            latch = self._request_latch
        if latch is not None:
            try:
                latch.wait()
            except Exception:
                pass

    # ------------------------------------------------------------------
    # Internals
    # ------------------------------------------------------------------
    def _listen(self, port: int) -> socket.socket:
        # Brief retry on EADDRINUSE: this container's ephemeral range
        # starts at 16000 — inside the listener plan — so an outgoing
        # connection from code that doesn't route through
        # safe_create_connection (urllib, jax's gloo dials) can
        # transiently squat a listener port. Those connections are
        # short-lived; a few retries ride them out. A port held by a
        # real listener still fails fast after the last attempt.
        last_error: OSError | None = None
        for attempt in range(5):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                s.bind((self.bind_host, port))
                s.listen(128)
                return s
            except OSError as e:
                s.close()
                if e.errno != errno.EADDRINUSE:
                    raise
                last_error = e
                time.sleep(0.05 * (attempt + 1))
        raise last_error  # type: ignore[misc]

    def _accept_loop(self, listener: socket.socket, plane: str) -> None:
        while self._running:
            try:
                conn, _addr = listener.accept()
            except OSError:
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(
                target=self._conn_loop, args=(conn, plane),
                name=f"transport/conn@{self.label}-{plane}", daemon=True,
            )
            with self._conn_lock:
                self._conns.add(conn)
                # Prune finished reader threads so the list stays bounded on
                # long-lived servers with connection churn. Start under the
                # lock too: stop() snapshots this list and join()s every
                # entry — an appended-but-unstarted thread there raises
                # RuntimeError mid-shutdown.
                self._conn_threads = [x for x in self._conn_threads if x.is_alive()]
                self._conn_threads.append(t)
                t.start()

    def _conn_loop(self, conn: socket.socket, plane: str) -> None:
        try:
            while self._running:
                try:
                    msg = recv_frame(conn)
                except ConnectionClosed:
                    break
                except (TransportError, OSError) as e:
                    # Protocol violations (bad magic, oversized frame) must
                    # be diagnosable, not silently dropped.
                    if isinstance(e, TransportError):
                        logger.warning(
                            "%s dropping %s connection on bad frame: %s",
                            self.label, plane, e,
                        )
                    break
                if msg.is_shutdown():
                    break
                _RX_FRAMES[plane].inc()
                _RX_BYTES[plane].inc(len(msg.payload))
                if plane == "async":
                    self._work.enqueue((msg, None))
                    # size() takes the queue lock — skip it when the
                    # gauge is a disabled-mode no-op
                    if _QUEUE_DEPTH is not NULL_METRIC:
                        _QUEUE_DEPTH.set(self._work.size())
                else:
                    # Sync requests are handled inline on the connection
                    # thread so responses pair with their requests even with
                    # pipelining from one client connection.
                    self._handle_sync(msg, conn)
        finally:
            with self._conn_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _handle_sync(self, msg: TransportMessage, conn: socket.socket) -> None:
        t0 = time.monotonic()
        try:
            # Per-RPC: skip even the kwargs-dict build when tracing is
            # off. The client's trace context ("_tc") makes this handler
            # span a CHILD of the remote caller's span in the merged
            # /trace instead of a per-host island.
            with span_from_remote("transport", "sync_handle",
                                  msg.header.get("_tc"), server=self.label,
                                  code=msg.code) \
                    if tracing_enabled() else NULL_SPAN:
                resp = self.do_sync_recv(msg)
            if resp is None:
                resp = TransportMessage(code=msg.code)
            resp.response_code = int(MessageResponseCode.SUCCESS)
        except Exception as e:  # noqa: BLE001 — errors must cross the wire
            logger.exception("%s sync handler error", self.label)
            resp = TransportMessage(
                code=msg.code,
                header={"error": str(e)},
                response_code=int(MessageResponseCode.ERROR),
            )
        _HANDLE_SECONDS["sync"].observe(time.monotonic() - t0)
        try:
            send_frame(conn, resp)
            _TX_FRAMES.inc()
            _TX_BYTES.inc(len(resp.payload))
        except OSError:
            pass
        self._fire_request_latch()

    def _worker_loop(self) -> None:
        while True:
            msg, _ = self._work.dequeue()
            if msg.is_shutdown():
                return
            if _QUEUE_DEPTH is not NULL_METRIC:
                _QUEUE_DEPTH.set(self._work.size())
            t0 = time.monotonic()
            try:
                with span_from_remote("transport", "async_handle",
                                      msg.header.get("_tc"),
                                      server=self.label,
                                      code=msg.code) if tracing_enabled() \
                        else NULL_SPAN:
                    self.do_async_recv(msg)
            except Exception:  # noqa: BLE001
                logger.exception("%s async handler error", self.label)
            _HANDLE_SECONDS["async"].observe(time.monotonic() - t0)
            self._fire_request_latch()


def handler_response(header: dict[str, Any] | None = None, payload: bytes = b"",
                     code: int = 0) -> TransportMessage:
    return TransportMessage(code=code, header=header or {}, payload=payload)
