"""Fused RMS-norm Pallas kernel.

One VMEM pass: mean-square, rsqrt and scale fuse into a single kernel
instead of the separate reductions + elementwise XLA would otherwise
schedule through HBM for large rows. fp32 statistics regardless of input
dtype (matches the model's _rms_norm semantics). Differentiable via
recompute-through-reference VJP.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_BLOCK_ROWS = 128


def _reference_rms_norm(x, scale, eps=1e-6):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps).astype(x.dtype)) * scale.astype(x.dtype)


def _rms_kernel(x_ref, scale_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    normed = x * jax.lax.rsqrt(var + eps)
    o_ref[:] = (normed * scale_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm(x, scale, eps: float = 1e-6):
    """x (..., D), scale (D,) → same shape as x."""
    return _rms_forward(x, scale, eps)


def _rms_forward(x, scale, eps):
    import math

    orig_shape = x.shape
    d = orig_shape[-1]
    rows = math.prod(orig_shape[:-1]) if len(orig_shape) > 1 else 1
    flat = x.reshape(rows, d)

    block = min(DEFAULT_BLOCK_ROWS, rows)
    if rows % block:
        return _reference_rms_norm(x, scale, eps)
    # Sub-tile rows (vs the 128-lane register tiling) stay on the
    # reference path on real hardware; interpret mode has no tiling
    if jax.default_backend() == "tpu" and (d < 128 or rows < 8):
        return _reference_rms_norm(x, scale, eps)

    interpret = jax.default_backend() == "cpu"
    out = pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        grid=(rows // block,),
        in_specs=[
            pl.BlockSpec((block, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
        interpret=interpret,
    )(flat, scale)
    return out.reshape(orig_shape)


def _rms_fwd(x, scale, eps):
    return _rms_forward(x, scale, eps), (x, scale)


def _rms_bwd(eps, res, g):
    x, scale = res
    _, vjp = jax.vjp(lambda x, s: _reference_rms_norm(x, s, eps), x, scale)
    return vjp(g)


rms_norm.defvjp(_rms_fwd, _rms_bwd)
