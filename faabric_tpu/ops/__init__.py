"""Pallas TPU kernels for the hot device ops."""

from faabric_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_with_lse,
    merge_attention_blocks,
)
from faabric_tpu.ops.rms_norm import rms_norm

__all__ = ["flash_attention", "flash_attention_with_lse",
           "merge_attention_blocks", "rms_norm"]
