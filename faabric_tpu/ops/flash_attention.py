"""Fused causal flash attention as a Pallas TPU kernel.

The hot op of the flagship model, written for the memory hierarchy: per
(batch·head, q-block) grid step the Q tile sits in VMEM while the kernel
streams K/V blocks with the online-softmax recurrence — no (S, S) score
matrix ever materialises in HBM. fp32 running max/sum/accumulator, compute
in the input dtype on the MXU.

Training support comes from a custom VJP whose backward recomputes through
the reference jnp attention (flash-backward kernels are a later
optimisation); forward inference/benchmarks run the kernel.

On CPU (tests) the kernel runs in interpreter mode automatically.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _reference_attention(q, k, v, causal: bool = True):
    """Plain jnp attention (the model's _attention twin) — used for the
    backward pass and for numerics tests."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  causal_offset: int):
    """One grid step: one (batch·head, q-block). Refs (leading singleton is
    the folded batch·head block): q (1, block_q, d), k/v (1, s_k, d).
    ``causal_offset`` end-aligns the mask when s_k > s_q (query row i may
    see keys up to i + offset) — matching the reference's tril(k=s_k-s_q).

    Matmul operands stay in the input dtype (bf16 rides the MXU at full
    rate, accumulating in fp32 via preferred_element_type); only the
    softmax statistics and the accumulator live in fp32."""
    _, block_q, d = q_ref.shape
    s_k = k_ref.shape[1]
    n_k_blocks = s_k // block_k

    q_idx = pl.program_id(1)
    q_off = q_idx * block_q

    q = q_ref[0]
    scale = 1.0 / np.sqrt(d)

    def body(i, carry):
        m_prev, l_prev, acc = carry
        k_blk = k_ref[0, pl.ds(i * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(i * block_k, block_k), :]

        scores = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (block_q, block_k)
        if causal:
            q_pos = q_off + causal_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)

        m_cur = jnp.max(scores, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        correction = jnp.exp(m_prev - m_new)
        p = jnp.exp(scores - m_new[:, None])
        l_new = l_prev * correction + jnp.sum(p, axis=1)
        acc = acc * correction[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    m0 = jnp.full((block_q,), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((block_q,), dtype=jnp.float32)
    acc0 = jnp.zeros((block_q, d), dtype=jnp.float32)

    if causal:
        # Blocks strictly above the (offset) diagonal contribute nothing
        n_blocks = jnp.minimum(
            n_k_blocks,
            (q_off + causal_offset + block_q + block_k - 1) // block_k)
    else:
        n_blocks = n_k_blocks
    m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K):
    """Causal attention, (B, S, H, D) → (B, S, H, D)."""
    return _flash_forward(q, k, v, causal, block_q, block_k)


def _flash_forward(q, k, v, causal, block_q, block_k):
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    block_q = min(block_q, s_q)
    block_k = min(block_k, s_k)
    if s_q % block_q or s_k % block_k or (causal and s_q > s_k):
        # Ragged shapes — and the degenerate causal s_q > s_k case, where
        # fully-masked query rows need the reference's uniform-softmax
        # treatment rather than a 0/0 accumulator — use the reference path
        return _reference_attention(q, k, v, causal)

    # Fold (B, H) into the grid's first axis; kernel sees 2-D tiles
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, s_q, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, s_k, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, s_k, d)

    interpret = jax.default_backend() == "cpu"
    kernel = functools.partial(_flash_kernel, block_k=block_k, causal=causal,
                               causal_offset=s_k - s_q)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, s_q // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, s_k, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, s_k, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, s_q, d).transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, causal, block_q, block_k):
    out = _flash_forward(q, k, v, causal, block_q, block_k)
    return out, (q, k, v)


def _flash_bwd(causal, block_q, block_k, res, g):
    q, k, v = res
    # Recompute-through-reference backward: numerically matches the
    # kernel's forward (same softmax), costs one extra forward
    _, vjp = jax.vjp(lambda q, k, v: _reference_attention(q, k, v, causal),
                     q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
