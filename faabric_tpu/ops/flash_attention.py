"""Fused causal flash attention as Pallas TPU kernels (fwd + bwd).

The hot op of the flagship model, written for the memory hierarchy: per
(batch·head, q-block) grid step the Q tile sits in VMEM while the kernel
streams K/V blocks with the online-softmax recurrence — no (S, S) score
matrix ever materialises in HBM. fp32 running max/sum/accumulator, compute
in the input dtype on the MXU.

Training runs the standard two-pass flash backward: the forward kernel
additionally emits the per-row log-sum-exp, and two backward kernels
recompute probabilities in-block from (Q, K, LSE) — one gridded over
q-blocks producing dQ, one over k-blocks producing dK/dV. Peak memory
stays O(S·D) in both directions (VERDICT r2 §weak-3: the old backward
recomputed through plain jnp attention, materialising (S, S) scores).

On CPU (tests) the kernels run in interpreter mode automatically.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30
# Per-row softmax statistics (lse, delta) ride through Pallas with a
# broadcast 128-lane trailing dim: Mosaic requires the last two block
# dims to be (8k, 128k)-tileable, so a (1, block_q) block of a 2-D
# (B·H, S) array cannot lower on real TPU hardware (the official TPU
# flash kernel uses the same layout for its m/l statistics).
LANE = 128


def _stat_cols(stat, n_cols: int):
    """Expand a (rows, LANE) lane-broadcast statistic to (rows, n_cols)
    (every lane holds the same per-row value; n_cols may be < LANE on the
    CPU interpret path)."""
    reps = max(1, -(-n_cols // LANE))
    return jnp.tile(stat, (1, reps))[:, :n_cols]


def _reference_attention(q, k, v, causal: bool = True):
    """Plain jnp attention (the model's _attention twin) — used for the
    backward pass and for numerics tests."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                  causal: bool, causal_offset: int):
    """One grid step: one (batch·head, q-block). Refs (leading singleton is
    the folded batch·head block): q (1, block_q, d), k/v (1, s_k, d).
    ``causal_offset`` end-aligns the mask when s_k > s_q (query row i may
    see keys up to i + offset) — matching the reference's tril(k=s_k-s_q).

    Matmul operands stay in the input dtype (bf16 rides the MXU at full
    rate, accumulating in fp32 via preferred_element_type); only the
    softmax statistics and the accumulator live in fp32."""
    _, block_q, d = q_ref.shape
    s_k = k_ref.shape[1]
    n_k_blocks = s_k // block_k

    q_idx = pl.program_id(1)
    q_off = q_idx * block_q

    q = q_ref[0]
    scale = 1.0 / np.sqrt(d)

    def body(i, carry):
        m_prev, l_prev, acc = carry
        k_blk = k_ref[0, pl.ds(i * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(i * block_k, block_k), :]

        scores = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (block_q, block_k)
        if causal:
            q_pos = q_off + causal_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)

        m_cur = jnp.max(scores, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        correction = jnp.exp(m_prev - m_new)
        p = jnp.exp(scores - m_new[:, None])
        l_new = l_prev * correction + jnp.sum(p, axis=1)
        acc = acc * correction[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    m0 = jnp.full((block_q,), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((block_q,), dtype=jnp.float32)
    acc0 = jnp.zeros((block_q, d), dtype=jnp.float32)

    if causal:
        # Blocks strictly above the (offset) diagonal contribute nothing
        n_blocks = jnp.minimum(
            n_k_blocks,
            (q_off + causal_offset + block_q + block_k - 1) // block_k)
    else:
        n_blocks = n_k_blocks
    m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    # Per-row log-sum-exp: the only softmax statistic the backward needs
    # (broadcast across the LANE dim — see LANE comment above)
    lse_ref[0] = jnp.broadcast_to((m + jnp.log(l))[:, None],
                                  (block_q, LANE))


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k: int, causal: bool,
                         causal_offset: int):
    """dQ pass: one grid step per (batch·head, q-block). Streams K/V blocks,
    recomputing P from (Q, K, LSE) — the (S, S) matrix never exists."""
    _, block_q, d = q_ref.shape
    s_k = k_ref.shape[1]
    n_k_blocks = s_k // block_k
    q_off = pl.program_id(1) * block_q
    scale = 1.0 / np.sqrt(d)

    q = q_ref[0]
    do = do_ref[0].astype(jnp.float32)
    # (block_q, LANE) lane-broadcast stats → expand across the k lanes
    lse = _stat_cols(lse_ref[0], block_k)
    delta = _stat_cols(delta_ref[0], block_k)

    def body(i, dq_acc):
        k_blk = k_ref[0, pl.ds(i * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(i * block_k, block_k), :]

        scores = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = q_off + causal_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)

        p = jnp.exp(scores - lse)  # masked entries underflow to 0
        dp = jax.lax.dot_general(
            do.astype(v_blk.dtype), v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq_acc + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        n_blocks = jnp.minimum(
            n_k_blocks,
            (q_off + causal_offset + block_q + block_k - 1) // block_k)
    else:
        n_blocks = n_k_blocks
    dq = jax.lax.fori_loop(0, n_blocks, body,
                           jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, causal: bool,
                          causal_offset: int):
    """dK/dV pass: one grid step per (batch·head, k-block), streaming
    q-blocks from the first causally-visible one."""
    _, block_k, d = k_ref.shape
    s_q = q_ref.shape[1]
    n_q_blocks = s_q // block_q
    k_off = pl.program_id(1) * block_k
    scale = 1.0 / np.sqrt(d)

    k = k_ref[0]
    v = v_ref[0]

    def body(j, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[0, pl.ds(j * block_q, block_q), :]
        do_blk = do_ref[0, pl.ds(j * block_q, block_q), :]
        lse_blk = _stat_cols(lse_ref[0, pl.ds(j * block_q, block_q), :],
                             block_k)
        delta_blk = _stat_cols(delta_ref[0, pl.ds(j * block_q, block_q), :],
                               block_k)

        scores = jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = j * block_q + causal_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_off + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)

        p = jnp.exp(scores - lse_blk)
        dv_acc = dv_acc + jax.lax.dot_general(
            p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_blk) * scale
        dk_acc = dk_acc + jax.lax.dot_general(
            ds.astype(q_blk.dtype), q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    if causal:
        # First q-block whose last row (j·bq + bq − 1 + offset) reaches this
        # k-block: ceil((k_off − offset − bq + 1) / bq) = floor((k_off − offset) / bq)
        j_start = jnp.maximum(0, (k_off - causal_offset) // block_q)
    else:
        j_start = 0
    zeros = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(j_start, n_q_blocks, body, (zeros, zeros))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _uses_kernel(q_shape, k_shape, causal, block_q, block_k) -> bool:
    s_q, s_k = q_shape[1], k_shape[1]
    d = q_shape[-1]
    # On real TPU hardware, sub-tile shapes (short sequences / narrow
    # heads vs the 128-lane register tiling) stay on the reference path —
    # Mosaic lowering of tiny blocks is at best wasteful padding. CPU
    # interpret mode has no tiling, so tests exercise small shapes.
    if jax.default_backend() == "tpu" and (
            s_q < DEFAULT_BLOCK_Q or s_k < DEFAULT_BLOCK_K or d < 64):
        return False
    block_q = min(block_q, s_q)
    block_k = min(block_k, s_k)
    # The lane-broadcast stats layout needs Mosaic-tileable blocks
    if jax.default_backend() == "tpu" and (block_q % 8 or block_k % LANE):
        return False
    # Ragged shapes — and the degenerate causal s_q > s_k case, where
    # fully-masked query rows need the reference's uniform-softmax
    # treatment rather than a 0/0 accumulator — use the reference path
    return not (s_q % block_q or s_k % block_k or (causal and s_q > s_k))


def _fold_heads(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K):
    """Causal attention, (B, S, H, D) → (B, S, H, D)."""
    out, _ = _flash_forward(q, k, v, causal, block_q, block_k)
    return out


def _flash_forward(q, k, v, causal, block_q, block_k):
    """Returns (out, lse) — lse is None on the reference fallback path,
    (B·H, S_q, LANE) lane-broadcast fp32 otherwise (slice ``[:, :, 0]``
    for the per-row value; kept 3-D so the backward can feed it straight
    back into the kernels without re-materializing the broadcast)."""
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    if not _uses_kernel(q.shape, k.shape, causal, block_q, block_k):
        return _reference_attention(q, k, v, causal), None
    block_q = min(block_q, s_q)
    block_k = min(block_k, s_k)

    # Fold (B, H) into the grid's first axis; kernel sees 2-D tiles
    qf, kf, vf = _fold_heads(q), _fold_heads(k), _fold_heads(v)

    interpret = jax.default_backend() == "cpu"
    kernel = functools.partial(_flash_kernel, block_k=block_k, causal=causal,
                               causal_offset=s_k - s_q)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, s_q // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, s_k, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, s_k, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LANE), lambda bh, qi: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, s_q, LANE), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, s_q, d).transpose(0, 2, 1, 3), lse


def _flash_fwd(q, k, v, causal, block_q, block_k):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _run_bwd_kernels(q, k, v, g_out, out, lse_l, causal, block_q, block_k,
                     g_lse=None):
    """Launch the two-pass backward kernels. ``lse_l`` is the forward
    kernel's (B·H, S_q, LANE) lane-broadcast statistic, fed back verbatim.
    ``g_lse`` (the lse output's cotangent, when the caller exposed lse)
    folds into the row correction: ds = p·(dp − (Δ − g_lse)), since
    ∂lse/∂s = p."""
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    block_q = min(block_q, s_q)
    block_k = min(block_k, s_k)

    qf, kf, vf = _fold_heads(q), _fold_heads(k), _fold_heads(v)
    dof, of = _fold_heads(g_out), _fold_heads(out)
    # delta_i = Σ_d dO·O — the softmax-jacobian row correction, O(S·D)
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1)
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32)
    # Lane-broadcast layout for the in-kernel stats (see LANE comment)
    delta_l = jnp.broadcast_to(delta[..., None], (*delta.shape, LANE))

    interpret = jax.default_backend() == "cpu"
    offset = s_k - s_q

    dq_kernel = functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                                  causal=causal, causal_offset=offset)
    dqf = pl.pallas_call(
        dq_kernel,
        grid=(b * h, s_q // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, s_k, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, s_k, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LANE), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LANE), lambda bh, qi: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, dof, lse_l, delta_l)

    dkv_kernel = functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                                   causal=causal, causal_offset=offset)
    dkf, dvf = pl.pallas_call(
        dkv_kernel,
        grid=(b * h, s_k // block_k),
        in_specs=[
            pl.BlockSpec((1, s_q, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, s_q, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, s_q, LANE), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, s_q, LANE), lambda bh, ki: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s_k, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, s_k, d), v.dtype),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lse_l, delta_l)

    def unfold(x, s):
        return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)

    return unfold(dqf, s_q), unfold(dkf, s_k), unfold(dvf, s_k)


def _flash_bwd(causal, block_q, block_k, res, g):
    q, k, v, out, lse = res
    if lse is None:
        # Forward fell back to reference numerics; match them in reverse
        _, vjp = jax.vjp(
            lambda q, k, v: _reference_attention(q, k, v, causal), q, k, v)
        return vjp(g)
    return _run_bwd_kernels(q, k, v, g, out, lse, causal, block_q, block_k)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# (out, lse) variant — the building block for flash-decoding-style block
# merging: partial attentions over key blocks combine exactly via
#   lse = logaddexp(lse_a, lse_b)
#   out = out_a·exp(lse_a − lse) + out_b·exp(lse_b − lse)
# ---------------------------------------------------------------------------

def _reference_lse(q, k, causal: bool):
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    # (B, H, S_q) → fold to the kernel's (B·H, S_q) layout
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    b, h, s_q = lse.shape
    return lse.reshape(b * h, s_q)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention_with_lse(q, k, v, causal: bool = True,
                             block_q: int = DEFAULT_BLOCK_Q,
                             block_k: int = DEFAULT_BLOCK_K):
    """Attention plus the per-row log-sum-exp: (out (B,S,H,D),
    lse (B·H, S) fp32). Differentiable in BOTH outputs — the lse
    cotangent folds into the existing backward kernels as a delta
    adjustment (ds = p·(dp − (Δ − g_lse)), since ∂lse/∂s = p)."""
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k)
    if lse is None:  # reference fallback path
        return out, _reference_lse(q, k, causal)
    return out, lse[:, :, 0]


def _flash_lse_fwd(q, k, v, causal, block_q, block_k):
    out, kernel_lse = _flash_forward(q, k, v, causal, block_q, block_k)
    lse = (kernel_lse[:, :, 0] if kernel_lse is not None
           else _reference_lse(q, k, causal))
    return (out, lse), (q, k, v, out, kernel_lse)


def _flash_lse_bwd(causal, block_q, block_k, res, cotangents):
    g_out, g_lse = cotangents
    q, k, v, out, lse = res
    if lse is None:
        # Reference numerics in reverse for the fallback path
        def ref(q, k, v):
            return (_reference_attention(q, k, v, causal),
                    _reference_lse(q, k, causal))

        _, vjp = jax.vjp(ref, q, k, v)
        return vjp((g_out, g_lse))
    return _run_bwd_kernels(q, k, v, g_out, out, lse, causal,
                            block_q, block_k, g_lse=g_lse)


flash_attention_with_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def merge_attention_blocks(outs, lses):
    """Combine partial attentions over disjoint key blocks (each an
    (out, lse) pair from flash_attention_with_lse) into the attention
    over their union — the flash-decoding merge."""
    lse_total = lses[0]
    for l in lses[1:]:
        lse_total = jnp.logaddexp(lse_total, l)
    b_h, s_q = lse_total.shape
    out = None
    for o, l in zip(outs, lses):
        # lse layout (B·H, S) → broadcast over (B, S, H, D)
        w = jnp.exp(l - lse_total)
        b = o.shape[0]
        h = b_h // b
        w = w.reshape(b, h, s_q).transpose(0, 2, 1)[..., None]
        term = o.astype(jnp.float32) * w
        out = term if out is None else out + term
    return out.astype(outs[0].dtype), lse_total
