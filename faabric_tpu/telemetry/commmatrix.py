"""Per-link communication matrix: who sends how much to whom, over what.

Every REMOTE send is attributed to a ``(src rank, dst rank, plane)``
cell — plane ∈ {``ptp`` (shared RPC plane), ``bulk-tcp`` (dedicated
tuned-socket data plane), ``shm`` (same-machine ring), ``device`` (the
compiled device collective plane: each rank's contribution attributed
to its mesh ring-neighbour — XLA owns the actual schedule, the row
records that the payload entered the device plane and NOT the host
planes)} — counting messages, payload bytes and a small send-latency
histogram. Same-host in-process queue delivery is deliberately NOT
counted: it is the 6 GiB/s hot path and carries no wire to attribute.

Adaptive wire codecs (ISSUE 11): cells additionally key on the wire
``codec`` (``raw`` / ``delta`` / ``delta-full`` / ``zlib``) and account
BOTH ``bytes`` (what crossed the wire) and ``bytes_raw`` (the pre-codec
payload), so compression shows up as a per-link ratio instead of
silently under-reporting traffic — and the governor's per-link decision
is asserted straight off the rows (``codec=`` in the dist tests).

This is the data HiCCL-style collective tuning needs before any
optimization: the 0.62-vs-6.01 GiB/s allreduce gap stops being a single
mystery number once each (src, dst, plane) link reports its own
bytes/latency and the bench's attribution report ranks the suspects.

Cardinality guard: ranks ≥ ``FAABRIC_COMMMATRIX_MAX_RANKS`` (default 64)
collapse into one ``other`` bucket per direction, so a 256-rank world
yields at most (N+1)² × 3 series instead of 196k — ``/metrics`` stays
kilobytes.

Export: ``snapshot()`` is the JSON-safe wire form riding GET_TELEMETRY;
``families()`` renders the same cells in the metrics-registry snapshot
schema so the planner can merge them into the Prometheus ``/metrics``
page (labels ``src``, ``dst``, ``plane`` + the per-host ``host`` label).
"""

from __future__ import annotations

import os
import threading

from faabric_tpu.telemetry.metrics import metrics_enabled

PLANES = ("ptp", "bulk-tcp", "shm", "device")

# Send-latency buckets (seconds): sub-ms ring pushes to multi-second
# wedged sockets. Coarser than DEFAULT_BUCKETS — per-link histograms
# multiply by rank-pair cardinality.
LATENCY_BUCKETS = (0.0001, 0.001, 0.01, 0.1, 1.0, 10.0)

DEFAULT_MAX_RANKS = 64
OTHER = "other"


class _Cell:
    __slots__ = ("messages", "bytes", "bytes_raw", "lat_sum", "lat_count",
                 "lat_counts", "_lock")

    def __init__(self) -> None:
        self.messages = 0
        self.bytes = 0       # WIRE bytes: what actually crossed the link
        self.bytes_raw = 0   # pre-codec payload bytes (== bytes for raw)
        self.lat_sum = 0.0
        self.lat_count = 0
        self.lat_counts = [0] * len(LATENCY_BUCKETS)
        self._lock = threading.Lock()

    def add(self, nbytes: int, seconds: float | None,
            raw_bytes: int | None = None) -> None:
        with self._lock:
            self.messages += 1
            self.bytes += nbytes
            self.bytes_raw += nbytes if raw_bytes is None else raw_bytes
            if seconds is not None:
                self.lat_sum += seconds
                self.lat_count += 1
                for i, ub in enumerate(LATENCY_BUCKETS):
                    if seconds <= ub:
                        self.lat_counts[i] += 1
                        break


class _NullCommMatrix:
    """Shared no-op returned while metrics are disabled."""

    __slots__ = ()

    def record(self, src, dst, plane, nbytes, seconds=None,
               raw_bytes=None, codec="raw") -> None:
        pass

    def snapshot(self) -> dict:
        return {}

    def families(self) -> dict:
        return {}

    def reset(self) -> None:
        pass


NULL_COMM_MATRIX = _NullCommMatrix()


class CommMatrix:
    def __init__(self, max_ranks: int | None = None) -> None:
        if max_ranks is None:
            try:
                max_ranks = int(os.environ.get(
                    "FAABRIC_COMMMATRIX_MAX_RANKS", DEFAULT_MAX_RANKS))
            except ValueError:
                # Malformed knob degrades to the default; the matrix is
                # created lazily from send hot paths and must not raise
                max_ranks = DEFAULT_MAX_RANKS
        self.max_ranks = max_ranks
        self._lock = threading.Lock()
        # (src_label, dst_label, plane) → _Cell; cell creation takes the
        # registry lock, updates take only the cell's own
        self._cells: dict[tuple, _Cell] = {}
        # Raw (src, dst, plane) → _Cell fast path: chunk-pipelined
        # collectives record one row per 4 MiB frame, so the per-record
        # cost must be one dict hit + one cell add, not two label
        # conversions. Only in-range ranks are cached (the `other`
        # bucket's raw key space is unbounded).
        self._fast: dict[tuple, _Cell] = {}

    def _rank_label(self, rank) -> str:
        try:
            r = int(rank)
        except (TypeError, ValueError):
            return OTHER
        return str(r) if 0 <= r < self.max_ranks else OTHER

    def record(self, src, dst, plane: str, nbytes: int,
               seconds: float | None = None,
               raw_bytes: int | None = None, codec: str = "raw") -> None:
        """``nbytes`` is what crossed the WIRE; ``raw_bytes`` the
        pre-codec payload size (compression must never make the matrix
        under-report traffic — both are accounted). ``codec`` keys the
        cell, so one link's raw and delta frames land in separate rows
        and the governor's per-link decision is directly observable."""
        raw = (src, dst, plane, codec)
        cell = self._fast.get(raw)
        if cell is None:
            labels = (self._rank_label(src), self._rank_label(dst), plane,
                      codec)
            with self._lock:
                cell = self._cells.setdefault(labels, _Cell())
                if labels[0] is not OTHER and labels[1] is not OTHER:
                    self._fast[raw] = cell
        cell.add(int(nbytes), seconds, raw_bytes)

    # -- export ---------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-safe wire form: ``{"max_ranks", "cells": [...]}`` with one
        row per live (src, dst, plane)."""
        with self._lock:
            items = list(self._cells.items())
        cells = []
        for (src, dst, plane, codec), c in items:
            with c._lock:
                cells.append({
                    "src": src, "dst": dst, "plane": plane,
                    "codec": codec,
                    "messages": c.messages, "bytes": c.bytes,
                    "bytes_raw": c.bytes_raw,
                    "lat_sum": round(c.lat_sum, 9),
                    "lat_count": c.lat_count,
                    "lat_buckets": [[b, n] for b, n in
                                    zip(LATENCY_BUCKETS, c.lat_counts)],
                })
        cells.sort(key=lambda r: -r["bytes"])
        return {"max_ranks": self.max_ranks, "cells": cells}

    def families(self) -> dict:
        """The same cells in the metrics-registry ``snapshot()`` schema,
        mergeable by ``render_snapshots`` into Prometheus exposition."""
        return families_from_cells(self.snapshot().get("cells", []))

    def reset(self) -> None:
        with self._lock:
            self._cells.clear()
            self._fast.clear()


def families_from_cells(cells: list[dict]) -> dict:
    """Registry-schema families from a snapshot's cell rows (used both
    process-locally and planner-side on scraped worker snapshots)."""
    msgs, byts, raws, lat = [], [], [], []
    for c in cells:
        labels = {"src": c["src"], "dst": c["dst"], "plane": c["plane"],
                  "codec": c.get("codec", "raw")}
        msgs.append({"labels": labels, "value": c["messages"]})
        byts.append({"labels": labels, "value": c["bytes"]})
        raws.append({"labels": labels,
                     "value": c.get("bytes_raw", c["bytes"])})
        lat.append({"labels": labels, "sum": c.get("lat_sum", 0.0),
                    "count": c.get("lat_count", 0),
                    "buckets": c.get("lat_buckets", [])})
    if not cells:
        return {}
    return {
        "faabric_comm_messages_total": {
            "type": "counter",
            "help": "Remote messages sent per (src, dst, plane, codec) "
                    "link",
            "series": msgs},
        "faabric_comm_bytes_total": {
            "type": "counter",
            "help": "Remote WIRE bytes sent per (src, dst, plane, codec) "
                    "link",
            "series": byts},
        "faabric_comm_raw_bytes_total": {
            "type": "counter",
            "help": "Pre-codec payload bytes per (src, dst, plane, "
                    "codec) link — compression never under-reports "
                    "traffic",
            "series": raws},
        "faabric_comm_send_seconds": {
            "type": "histogram",
            "help": "Per-message send latency per (src, dst, plane, "
                    "codec) link",
            "series": lat},
    }


def merge_cell_rows(per_host: dict[str, list[dict]]) -> list[dict]:
    """Merge hosts' cell rows for the JSON ``/commmatrix`` totals view:
    same (src, dst, plane) across hosts sums (each host only reports its
    own outbound sends, so summing never double-counts)."""
    merged: dict[tuple, dict] = {}
    for _host, cells in per_host.items():
        for c in cells:
            codec = c.get("codec", "raw")
            key = (c["src"], c["dst"], c["plane"], codec)
            m = merged.get(key)
            if m is None:
                merged[key] = {"src": c["src"], "dst": c["dst"],
                               "plane": c["plane"], "codec": codec,
                               "messages": 0, "bytes": 0, "bytes_raw": 0,
                               "lat_sum": 0.0, "lat_count": 0}
                m = merged[key]
            m["messages"] += c.get("messages", 0)
            m["bytes"] += c.get("bytes", 0)
            m["bytes_raw"] += c.get("bytes_raw", c.get("bytes", 0))
            m["lat_sum"] += c.get("lat_sum", 0.0)
            m["lat_count"] += c.get("lat_count", 0)
    out = list(merged.values())
    out.sort(key=lambda r: -r["bytes"])
    return out


_matrix: CommMatrix | None = None
_matrix_lock = threading.Lock()


def get_comm_matrix() -> CommMatrix | _NullCommMatrix:
    if not metrics_enabled():
        return NULL_COMM_MATRIX
    global _matrix
    if _matrix is None:
        with _matrix_lock:
            if _matrix is None:
                _matrix = CommMatrix()
    return _matrix
