"""Always-on in-process sampling profiler with per-thread CPU and
GIL-pressure attribution (ISSUE 18).

Every observability plane so far answers *where the time goes between
processes* — the per-link comm profiles, the invocation phase ledger,
the state access ledger. This module answers *where the CPU goes inside
a process*: a background thread snapshots ``sys._current_frames()``
every ``FAABRIC_PROFILE_INTERVAL_MS`` (default 25 ms) into a bounded,
cardinality-capped stack trie keyed by thread *class* — the
``subsystem/role`` prefix of the thread name, so samples read as
``planner/tick`` or ``bulk/conn``, never ``Thread-7``.

Two attribution signals separate wall-blocked from CPU-burning frames:

* each sample is weighted by the real per-thread CPU delta read from
  ``/proc/self/task/<tid>/stat`` (the procstats parsing idiom), so a
  thread parked in ``select()`` accrues samples but ~zero ``cpu_ms``
  while a busy-spin accrues both;
* a GIL-pressure estimator: the sampler knows exactly when it *asked*
  to wake and when it actually ran, and under a contended GIL that
  drift grows with the interpreter's switch interval; combined with a
  census of runnable threads (those with a CPU delta in the last
  period) it yields a [0, 1] gauge per process. The doctor cross-checks
  it against the lockcheck hold-time histograms so a lock convoy is not
  misread as GIL saturation.

Surfacing follows the established plane pattern end to end: a
``profile`` block on GET_TELEMETRY, planner-merged and per-host
``GET /profile``, ``faabric_profile_*`` / ``faabric_gil_pressure`` on
/metrics and /timeseries, ``python -m faabric_tpu.runner.profile``
(top-down / bottom-up / flamegraph-collapsed / diff / selftest), and
doctor analyzers ``cpu_hotspot`` / ``gil_saturation`` /
``sampler_starved``.

Knobs:

* ``FAABRIC_PROFILE`` — default on; ``0`` pins the whole module to the
  shared no-op singleton (one attribute read + early return).
* ``FAABRIC_PROFILE_INTERVAL_MS`` — sampling period (default 25).
* ``FAABRIC_PROFILE_MAX_NODES`` — process-wide trie node budget
  (default 4096); overflow folds into a reserved ``(trie-cap)`` child
  and counts ``dropped_frames``, so memory is bounded no matter what
  the workload's stacks look like.
* ``FAABRIC_PROFILE_MAX_DEPTH`` — frames kept per stack (default 40,
  innermost kept, outermost folded).

Lifecycle mirrors the timeseries sampler: refcounted
``start_profiler()`` / ``stop_profiler()`` so PlannerServer and
WorkerRuntime (possibly co-resident in one process) share a single
sampler thread and the leak gate sees zero extras after the last
``stop()``.
"""

from __future__ import annotations

import os
import re
import sys
import threading
import time

from .metrics import get_metrics, metrics_enabled
from .timeseries import get_timeseries

_CLK_TCK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100

DEFAULT_INTERVAL_MS = 25.0
DEFAULT_MAX_NODES = 4096
DEFAULT_MAX_DEPTH = 40

# Reserved frame labels — never produced by _frame_label, so they can't
# collide with real code locations.
CAP_LABEL = "(trie-cap)"
TRUNC_LABEL = "(deep-stack)"

# A thread whose per-interval CPU delta exceeds this fraction of the
# interval counts as "runnable" in the GIL census (it wanted the GIL
# for most of the period, not just a wakeup blip).
_RUNNABLE_FRACTION = 0.5

# EWMA smoothing for the drift ratio and the per-sample cost estimate.
_EWMA_ALPHA = 0.2

_TRAILING_NUM = re.compile(r"[-_]?\d+$")


def profile_enabled() -> bool:
    """Profiler master switch: requires the metrics plane (the trie is
    surfaced through it) and ``FAABRIC_PROFILE`` != 0 (default on)."""
    return metrics_enabled() and os.environ.get(
        "FAABRIC_PROFILE", "1") != "0"


def profile_interval_s() -> float:
    try:
        ms = float(os.environ.get("FAABRIC_PROFILE_INTERVAL_MS",
                                  DEFAULT_INTERVAL_MS))
    except ValueError:
        ms = DEFAULT_INTERVAL_MS
    return max(ms, 1.0) / 1000.0


def thread_class(name: str) -> str:
    """Collapse a thread name to its stable ``subsystem/role`` class.

    The repo-wide naming convention (ISSUE 18 satellite) is
    ``subsystem/role`` with an optional ``@instance`` suffix for
    per-connection / per-app threads (``bulk/conn@9031``,
    ``planner/recover@app7``). Classing strips the instance so the trie
    cardinality tracks the *kinds* of threads, not their count.
    Foreign threads (pytest, concurrent.futures, jax pools) fold under
    ``other/`` with trailing numerals stripped; anonymous ones are
    ``unnamed``.
    """
    if not name:
        return "unnamed"
    if name == "MainThread":
        return "main"
    base = name.split("@", 1)[0]
    if "/" in base:
        return base
    # CPython's "Thread-7 (target_name)" form: class by target.
    if base.startswith("Thread-"):
        if "(" in base and base.endswith(")"):
            target = base.split("(", 1)[1][:-1].strip()
            if target:
                return "other/" + target
        return "unnamed"
    return "other/" + (_TRAILING_NUM.sub("", base) or base)


def _frame_label(frame) -> str:
    """``name (pkg/file.py:lineno)`` with the path clipped to its last
    two components — stable across checkouts, unique enough to read."""
    code = frame.f_code
    path = code.co_filename.replace("\\", "/")
    parts = path.rsplit("/", 2)
    short = "/".join(parts[-2:]) if len(parts) > 1 else path
    return f"{code.co_name} ({short}:{code.co_firstlineno})"


class _Node:
    """One frame in a per-class stack trie (root→leaf = outer→inner)."""

    __slots__ = ("frame", "children", "samples", "cpu_ms")

    def __init__(self, frame: str) -> None:
        self.frame = frame
        self.children: dict[str, _Node] = {}
        self.samples = 0
        self.cpu_ms = 0.0


class _NullProfiler:
    """Shared no-op when the plane is off: every method one early
    return, so the disabled path costs an attribute read."""

    enabled = False

    def sample_now(self) -> None:
        pass

    def snapshot(self) -> dict:
        return {}

    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass


NULL_PROFILER = _NullProfiler()


class Profiler:
    """Bounded stack-trie sampler with per-thread CPU weighting.

    All trie / census state is folded under one leaf ``_lock`` per
    sample; the expensive reads (``sys._current_frames()``, the
    ``/proc/self/task`` scans) happen outside it. Nothing under
    ``_lock`` calls out of the module, so it can never participate in
    a lock cycle (concheck baseline stays EMPTY).
    """

    GUARDS = {
        "_roots": "_lock",
        "_class_threads": "_lock",
        "_samples": "_lock",
        "_expected": "_lock",
        "_nodes": "_lock",
        "_dropped": "_lock",
        "_cpu_prev": "_lock",
        "_drift_avg": "_lock",
        "_drift_max": "_lock",
        "_late": "_lock",
        "_runnable_now": "_lock",
        "_runnable_sum": "_lock",
        "_cost_avg_s": "_lock",
    }

    enabled = True

    def __init__(self, interval_s: float | None = None,
                 max_nodes: int | None = None,
                 max_depth: int | None = None) -> None:
        self.interval_s = interval_s or profile_interval_s()
        try:
            self.max_nodes = int(max_nodes or os.environ.get(
                "FAABRIC_PROFILE_MAX_NODES", DEFAULT_MAX_NODES))
        except ValueError:
            self.max_nodes = DEFAULT_MAX_NODES
        try:
            self.max_depth = int(max_depth or os.environ.get(
                "FAABRIC_PROFILE_MAX_DEPTH", DEFAULT_MAX_DEPTH))
        except ValueError:
            self.max_depth = DEFAULT_MAX_DEPTH
        self._lock = threading.Lock()
        self._roots: dict[str, _Node] = {}      # class -> trie root
        self._class_threads: dict[str, int] = {}
        self._samples = 0
        self._expected = 0
        self._nodes = 0
        self._dropped = 0
        self._cpu_prev: dict[int, float] = {}   # native tid -> cpu s
        self._drift_avg = 0.0
        self._drift_max = 0.0
        self._late = 0
        self._runnable_now = 0
        self._runnable_sum = 0.0
        self._cost_avg_s = 0.0
        self._started = time.monotonic()
        self._thread: threading.Thread | None = None
        self._stop_evt = threading.Event()
        m = get_metrics()
        self._m_samples = m.counter("faabric_profile_samples_total",
                                    "stack samples folded into the trie")
        self._m_nodes = m.gauge("faabric_profile_stack_nodes",
                                "live stack-trie nodes (bounded)")
        self._m_overhead = m.gauge(
            "faabric_profile_overhead_pct",
            "sampler self-cost as % of the sampling interval")
        self._m_gil = m.gauge(
            "faabric_gil_pressure",
            "0..1 sampler-drift + runnable-census GIL estimate")

    # ------------------------------------------------------------------
    # sampling

    @staticmethod
    def _read_thread_cpu() -> dict[int, float]:
        """native tid -> cumulative CPU seconds, from
        ``/proc/self/task/<tid>/stat`` (procstats parsing idiom: the
        comm field may contain spaces/parens, so split after the last
        ``)``; utime/stime are fields 14/15, i.e. offsets 11/12 after
        the split)."""
        out: dict[int, float] = {}
        try:
            tids = os.listdir("/proc/self/task")
        except OSError:
            return out
        for tid in tids:
            try:
                with open(f"/proc/self/task/{tid}/stat") as f:
                    rest = f.read().rsplit(")", 1)[-1].split()
                out[int(tid)] = (int(rest[11]) + int(rest[12])) / _CLK_TCK
            except (OSError, IndexError, ValueError):
                continue  # thread exited mid-scan
        return out

    def sample_now(self, drift_s: float = 0.0) -> None:
        """Take one sample: read frames + per-thread CPU outside the
        lock, fold everything in under it."""
        t0 = time.perf_counter()
        me = threading.get_ident()
        idents: dict[int, tuple[str, int | None]] = {}
        for t in threading.enumerate():
            if t.ident is not None and t.ident != me:
                idents[t.ident] = (t.name, t.native_id)
        try:
            frames = sys._current_frames()
        except Exception:
            return
        cpu_now = self._read_thread_cpu()

        # Pre-compute per-thread stacks and labels outside the lock;
        # only the trie fold itself mutates shared state.
        folds: list[tuple[int, list[str]]] = []
        for ident, frame in frames.items():
            info = idents.get(ident)
            if info is None:
                continue  # our own thread, or one that died mid-walk
            stack: list[str] = []
            f = frame
            while f is not None and len(stack) <= self.max_depth:
                stack.append(_frame_label(f))
                f = f.f_back
            stack.reverse()  # outermost first
            if len(stack) > self.max_depth:
                stack = [TRUNC_LABEL] + stack[-self.max_depth:]
            folds.append((ident, stack))

        interval = self.interval_s
        with self._lock:
            self._samples += 1
            self._expected += 1
            runnable = 0
            cpu_deltas: dict[int, float] = {}
            for tid, total in cpu_now.items():
                prev = self._cpu_prev.get(tid)
                if prev is not None and total > prev:
                    cpu_deltas[tid] = total - prev
                    if total - prev >= _RUNNABLE_FRACTION * interval:
                        runnable += 1
            self._cpu_prev = cpu_now
            self._runnable_now = runnable
            self._runnable_sum += runnable

            drift_ratio = max(drift_s, 0.0) / interval
            self._drift_avg += _EWMA_ALPHA * (drift_ratio
                                              - self._drift_avg)
            self._drift_max = max(self._drift_max, drift_ratio)
            if drift_ratio > 1.0:
                self._late += 1

            self._class_threads = {}
            for ident, stack in folds:
                name, native = idents[ident]
                cls = thread_class(name)
                self._class_threads[cls] = \
                    self._class_threads.get(cls, 0) + 1
                cpu_ms = cpu_deltas.get(native or -1, 0.0) * 1000.0
                self._fold_locked(cls, stack, cpu_ms)

            cost = time.perf_counter() - t0
            self._cost_avg_s += _EWMA_ALPHA * (cost - self._cost_avg_s)
            self._m_samples.inc()
            self._m_nodes.set(float(self._nodes))
            self._m_overhead.set(
                round(100.0 * self._cost_avg_s / interval, 3))
            self._m_gil.set(self.gil_pressure_locked())

    def _fold_locked(self, cls: str, stack: list[str],
                     cpu_ms: float) -> None:
        """Fold one stack into the class trie. Past the node budget new
        paths collapse into a reserved cap child and stop descending —
        counts stay exact, attribution degrades gracefully."""
        node = self._roots.get(cls)
        if node is None:
            node = self._roots[cls] = _Node("(root)")
            self._nodes += 1
        node.samples += 1
        node.cpu_ms += cpu_ms
        for frame in stack:
            child = node.children.get(frame)
            if child is None:
                if self._nodes >= self.max_nodes:
                    child = node.children.get(CAP_LABEL)
                    if child is None:
                        child = node.children[CAP_LABEL] = \
                            _Node(CAP_LABEL)
                    child.samples += 1
                    child.cpu_ms += cpu_ms
                    self._dropped += 1
                    return
                child = node.children[frame] = _Node(frame)
                self._nodes += 1
            child.samples += 1
            child.cpu_ms += cpu_ms
            node = child

    def note_missed(self, n: int) -> None:
        """Record sampler wakeups that never happened (scheduling
        starvation): expected grows, samples doesn't."""
        if n <= 0:
            return
        with self._lock:
            self._expected += n

    def gil_pressure_locked(self) -> float:
        """[0, 1] — EWMA sampler-wakeup drift clamped; drift is in
        units of the interval, so 1.0 means wakeups land a full period
        late on average."""
        return max(0.0, min(1.0, self._drift_avg))

    def snapshot_gil_pressure(self) -> float:
        """Single locked read for the /timeseries gauge closure."""
        with self._lock:
            return self.gil_pressure_locked()

    def snapshot_runnable(self) -> float:
        with self._lock:
            return float(self._runnable_now)

    # ------------------------------------------------------------------
    # sampler thread

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, name="telemetry/profiler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        t = self._thread
        if t is None:
            return
        self._stop_evt.set()
        t.join(timeout=5.0)
        self._thread = None

    def _run(self) -> None:
        """Fixed-cadence loop measuring its own wakeup drift.

        ``next_t`` advances by exactly one interval per iteration so
        drift is *measured*, not absorbed — but is clamped to ``now``
        when more than one whole period behind, so a long stall doesn't
        spiral into back-to-back catch-up sampling (the missed wakeups
        are recorded instead)."""
        interval = self.interval_s
        next_t = time.monotonic() + interval
        while not self._stop_evt.wait(
                timeout=max(next_t - time.monotonic(), 0.0)):
            now = time.monotonic()
            drift = now - next_t
            self.sample_now(drift_s=drift)
            next_t += interval
            if next_t < now:
                missed = int((now - next_t) / interval) + 1
                self.note_missed(missed)
                next_t = now + interval

    # ------------------------------------------------------------------
    # export

    def snapshot(self) -> dict:
        """Wire form for the telemetry block / worker ``/profile``."""
        with self._lock:
            wall = max(time.monotonic() - self._started, 1e-9)
            classes = {}
            for cls, root in sorted(self._roots.items()):
                classes[cls] = {
                    "samples": root.samples,
                    "cpu_ms": round(root.cpu_ms, 3),
                    "threads_now": self._class_threads.get(cls, 0),
                }
            samples = self._samples
            doc = {
                "enabled": True,
                "pid": os.getpid(),
                "interval_ms": round(self.interval_s * 1000.0, 3),
                "samples": samples,
                "expected_samples": self._expected,
                "wall_s": round(wall, 3),
                "sample_cost_ms": round(self._cost_avg_s * 1000.0, 4),
                "overhead_pct": round(
                    100.0 * self._cost_avg_s / self.interval_s, 3),
                "nodes": self._nodes,
                "max_nodes": self.max_nodes,
                "dropped_frames": self._dropped,
                "classes": classes,
                "stacks": self._leaf_rows_locked(),
                "gil": {
                    "pressure": round(self.gil_pressure_locked(), 4),
                    "drift_ratio_avg": round(self._drift_avg, 4),
                    "drift_ratio_max": round(self._drift_max, 4),
                    "runnable_now": self._runnable_now,
                    "runnable_avg": round(
                        self._runnable_sum / samples, 3)
                        if samples else 0.0,
                    "late_samples": self._late,
                },
            }
        return doc

    def _leaf_rows_locked(self, per_class_cap: int = 50) -> list[dict]:
        """Collapsed hot-path rows: one row per trie leaf (or per
        interior node where a stack actually *ended*), frames
        outer→inner. Capped per class with an ``(elided)`` fold row so
        the wire size is bounded like every other plane's export."""
        rows: list[dict] = []
        for cls, root in sorted(self._roots.items()):
            class_rows: list[dict] = []

            def walk(node: _Node, path: list[str]) -> None:
                child_samples = sum(c.samples
                                    for c in node.children.values())
                ended = node.samples - child_samples
                if path and (ended > 0 or not node.children):
                    child_cpu = sum(c.cpu_ms
                                    for c in node.children.values())
                    class_rows.append({
                        "class": cls,
                        "frames": list(path),
                        "samples": ended if node.children
                        else node.samples,
                        "cpu_ms": round(node.cpu_ms - child_cpu
                                        if node.children
                                        else node.cpu_ms, 3),
                    })
                for child in node.children.values():
                    walk(child, path + [child.frame])

            walk(root, [])
            class_rows.sort(key=lambda r: (-r["cpu_ms"],
                                           -r["samples"]))
            if len(class_rows) > per_class_cap:
                tail = class_rows[per_class_cap:]
                class_rows = class_rows[:per_class_cap]
                class_rows.append({
                    "class": cls,
                    "frames": ["(elided)"],
                    "samples": sum(r["samples"] for r in tail),
                    "cpu_ms": round(sum(r["cpu_ms"] for r in tail), 3),
                })
            rows.extend(class_rows)
        return rows


# ----------------------------------------------------------------------
# merge / render / CLI helpers (pure functions over wire forms)

def aggregate_profile(telemetry: dict) -> dict:
    """Merge per-host ``profile`` telemetry blocks into one ranked
    cluster document (the ``GET /profile`` payload)."""
    hosts: dict[str, dict] = {}
    for host, tel in sorted((telemetry or {}).items()):
        block = (tel or {}).get("profile")
        if block:
            hosts[host] = block

    classes: list[dict] = []
    stacks: list[dict] = []
    gil: dict[str, dict] = {}
    for host, block in hosts.items():
        for cls, row in (block.get("classes") or {}).items():
            classes.append({"host": host, "class": cls, **row})
        host_cpu = sum((r.get("cpu_ms") or 0.0)
                       for r in (block.get("stacks") or []))
        for row in (block.get("stacks") or []):
            stacks.append({
                "host": host,
                "class": row.get("class", "?"),
                "frames": row.get("frames") or [],
                "samples": row.get("samples", 0),
                "cpu_ms": row.get("cpu_ms", 0.0),
                "cpu_share": round((row.get("cpu_ms") or 0.0)
                                   / host_cpu, 4) if host_cpu else 0.0,
            })
        if block.get("gil"):
            gil[host] = block["gil"]

    classes.sort(key=lambda r: (-r["cpu_ms"], -r["samples"]))
    stacks.sort(key=lambda r: (-r["cpu_ms"], -r["samples"]))
    for i, row in enumerate(stacks):
        row["rank"] = i + 1
    return {
        "generated_at": time.time(),
        "hosts": {h: {k: b.get(k) for k in
                      ("pid", "interval_ms", "samples",
                       "expected_samples", "wall_s", "overhead_pct",
                       "nodes", "dropped_frames")}
                  for h, b in hosts.items()},
        "classes": classes,
        "stacks": stacks,
        "gil": gil,
    }


def render_profile(doc: dict, top: int = 15) -> str:
    """Fixed-width console rendering of an aggregated profile doc."""
    lines = []
    hosts = doc.get("hosts") or {}
    lines.append(f"cluster profile — {len(hosts)} host(s)")
    for host, meta in sorted(hosts.items()):
        g = (doc.get("gil") or {}).get(host) or {}
        lines.append(
            f"  {host}: {meta.get('samples', 0)} samples @ "
            f"{meta.get('interval_ms', '?')} ms, overhead "
            f"{meta.get('overhead_pct', 0)}%, gil_pressure "
            f"{g.get('pressure', 0)}, runnable_avg "
            f"{g.get('runnable_avg', 0)}")
    lines.append("")
    lines.append(f"{'rank':>4}  {'cpu_ms':>10}  {'smpl':>6}  "
                 f"{'share':>6}  host/class · leaf")
    for row in (doc.get("stacks") or [])[:top]:
        leaf = row["frames"][-1] if row.get("frames") else "?"
        lines.append(
            f"{row.get('rank', 0):>4}  {row.get('cpu_ms', 0):>10.1f}  "
            f"{row.get('samples', 0):>6}  "
            f"{row.get('cpu_share', 0):>6.2f}  "
            f"{row.get('host', '?')}/{row.get('class', '?')} · {leaf}")
    return "\n".join(lines)


def collapsed_lines(doc: dict, weight: str = "samples") -> list[str]:
    """Flamegraph-collapsed output: ``host;class;f1;f2;...;fN count``
    — feedable straight into flamegraph.pl / speedscope. ``weight`` is
    ``samples`` or ``cpu`` (cpu_ms rounded to int)."""
    out = []
    for row in doc.get("stacks") or []:
        w = (int(round(row.get("cpu_ms", 0.0))) if weight == "cpu"
             else row.get("samples", 0))
        if w <= 0:
            continue
        parts = [row.get("host", "?"), row.get("class", "?")] + \
            list(row.get("frames") or [])
        out.append(";".join(parts) + f" {w}")
    return out


def bottom_up(doc: dict, top: int = 15) -> list[dict]:
    """Leaf-frame aggregation: for each innermost frame, total self
    weight across all stacks it terminates — the 'which function burns
    the CPU' view, complementary to the top-down trie."""
    acc: dict[str, dict] = {}
    for row in doc.get("stacks") or []:
        frames = row.get("frames") or []
        if not frames:
            continue
        leaf = frames[-1]
        ent = acc.setdefault(leaf, {"frame": leaf, "samples": 0,
                                    "cpu_ms": 0.0, "classes": set()})
        ent["samples"] += row.get("samples", 0)
        ent["cpu_ms"] += row.get("cpu_ms", 0.0)
        ent["classes"].add(f"{row.get('host', '?')}/"
                           f"{row.get('class', '?')}")
    rows = sorted(acc.values(),
                  key=lambda r: (-r["cpu_ms"], -r["samples"]))[:top]
    for r in rows:
        r["cpu_ms"] = round(r["cpu_ms"], 3)
        r["classes"] = sorted(r["classes"])
    return rows


def diff_profiles(before: dict, after: dict, top: int = 15
                  ) -> list[dict]:
    """Round-over-round regression hunting: match stacks by
    (host, class, frames) and rank by cpu_ms growth."""
    def index(doc):
        return {(r.get("host"), r.get("class"),
                 tuple(r.get("frames") or [])): r
                for r in doc.get("stacks") or []}

    b, a = index(before), index(after)
    rows = []
    for key in set(b) | set(a):
        pb, pa = b.get(key), a.get(key)
        cpu_b = pb.get("cpu_ms", 0.0) if pb else 0.0
        cpu_a = pa.get("cpu_ms", 0.0) if pa else 0.0
        rows.append({
            "host": key[0], "class": key[1], "frames": list(key[2]),
            "cpu_ms_before": round(cpu_b, 3),
            "cpu_ms_after": round(cpu_a, 3),
            "cpu_ms_delta": round(cpu_a - cpu_b, 3),
            "samples_before": pb.get("samples", 0) if pb else 0,
            "samples_after": pa.get("samples", 0) if pa else 0,
        })
    rows.sort(key=lambda r: -abs(r["cpu_ms_delta"]))
    return rows[:top]


# ----------------------------------------------------------------------
# process-wide singleton + refcounted lifecycle

_profiler: Profiler | None = None
_profiler_users = 0
_singleton_lock = threading.Lock()


def _register_gauges(p: Profiler) -> None:
    """Best-effort /timeseries wiring (mirrors statestats): cheap
    closures over the profiler's locked state."""
    try:
        ring = get_timeseries()
        ring.register("gil_pressure",
                      lambda: p.snapshot_gil_pressure())
        ring.register("profile_runnable_threads",
                      lambda: p.snapshot_runnable())
    except Exception:
        pass


def _unregister_gauges() -> None:
    try:
        ring = get_timeseries()
        ring.unregister("gil_pressure")
        ring.unregister("profile_runnable_threads")
    except Exception:
        pass


def get_profiler() -> Profiler | _NullProfiler:
    """The process-wide profiler, or the shared no-op when disabled."""
    global _profiler
    if not profile_enabled():
        return NULL_PROFILER
    if _profiler is None:
        with _singleton_lock:
            if _profiler is None:
                p = Profiler()
                _register_gauges(p)
                _profiler = p
    return _profiler


def start_profiler() -> None:
    """Refcounted sampler start: the first caller spawns the thread,
    later callers (a WorkerRuntime sharing the planner's process) just
    bump the count. No-op when the plane is disabled."""
    global _profiler_users
    if not profile_enabled():
        return
    p = get_profiler()
    with _singleton_lock:
        _profiler_users += 1
        if _profiler_users == 1:
            p.start()  # concheck: ok(blocking-under-lock) — spawn only


def stop_profiler() -> None:
    """Refcounted stop: the last caller joins the sampler thread so
    the leak gate sees zero extras."""
    global _profiler_users
    with _singleton_lock:
        if _profiler_users == 0:
            return
        _profiler_users -= 1
        if _profiler_users > 0:
            return
        p = _profiler
    if p is not None:
        p.stop()


def reset_profiler() -> None:
    """Test hook: drop the singleton and its timeseries gauges."""
    global _profiler, _profiler_users
    with _singleton_lock:
        p, _profiler, _profiler_users = _profiler, None, 0
    if p is not None:
        p.stop()
        _unregister_gauges()


def profile_telemetry_block() -> dict:
    """The ``profile`` entry for GET_TELEMETRY's blocks selector —
    ``{}`` when the plane is off, so disabled hosts cost nothing on
    the wire."""
    p = get_profiler()
    if not p.enabled:
        return {}
    return p.snapshot()
