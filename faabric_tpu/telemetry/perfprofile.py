"""Rolling performance profiles: the layer that turns telemetry into
answers (ISSUE 12).

PRs 1/3 produced raw signals — metrics, cross-host spans, the per-link
comm matrix, the flight recorder — but every consumer that needed an
*interpretation* re-derived it ad hoc: the wire-codec governor windowed
the comm matrix for link bandwidth, and the upcoming schedule compiler
(ROADMAP 5; GC3 arXiv:2201.11840 selects schedules from measured
per-link profiles) has nothing to read at all. This module is the
feedback store they share:

- :class:`PerfProfileStore` — per-(dst-host, plane, codec, size-class)
  bandwidth/latency estimators (decayed streaming quantiles + EWMA,
  bounded cardinality like the comm matrix), fed from the bulk client,
  the shared RPC plane and the device plane. Each host profiles its OWN
  outbound links (same convention as the comm matrix); the planner's
  ``GET /perf`` tags rows with their source host and merges cluster-
  wide. Profiles persist to ``FAABRIC_PERF_PROFILE_DIR`` and re-seed
  the store at boot, so a restarted process starts from measured link
  speeds instead of the assume-slow default.
- :class:`CollectiveProfiler` — per-(world, collective, round) phase
  fold-in from the MPI and device planes: every rank records its round
  ENTRY timestamp (wall-anchored, the tracer convention) plus per-phase
  durations (intra/leader/redistribute, compile/execute) and a total.
  :func:`critical_path` decomposes which rank/phase bounded each round;
  :func:`find_stragglers` flags ranks consistently ARRIVING late
  (entry-skew, not totals — in a synchronous collective the straggler
  inflates *everyone's* total, so totals cannot identify it; the late
  arrival can). Detections emit ``faabric_straggler_*`` metrics, flight
  records and trace instant events.
- Pure merge/analysis helpers (:func:`merge_link_rows`,
  :func:`merge_collective_series`, :func:`aggregate_perf`) shared by
  the planner's ``/perf`` aggregation and the cluster doctor
  (``python -m faabric_tpu.runner.doctor``), which also runs them on
  dumped files — post-mortem diagnosis needs no live cluster.

Knobs: ``FAABRIC_PERF_PROFILE`` (``0`` disables both stores even with
metrics on), ``FAABRIC_PERF_HALF_LIFE_S`` (estimator decay half-life,
default 120), ``FAABRIC_PERF_MAX_LINKS`` (cardinality cap, default 512;
overflow collapses into an ``other`` destination), ``FAABRIC_PERF_DIR``
alias ``FAABRIC_PERF_PROFILE_DIR`` (persistence directory; unset → no
persistence), ``FAABRIC_PERF_PERSIST_S`` (throttle, default 30),
``FAABRIC_PERF_ROUNDS`` (per-collective round window, default 32),
``FAABRIC_STRAGGLER_FACTOR`` (entry-skew threshold as a fraction of the
median round total, default 0.25), ``FAABRIC_STRAGGLER_MIN_ROUNDS``
(consecutive evidence floor, default 3).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time

from faabric_tpu.telemetry.metrics import get_metrics, metrics_enabled
from faabric_tpu.util.config import _env_float, _env_int

# -- estimator geometry -------------------------------------------------
# Quantile buckets: geometric grid with 2 buckets per octave, spanning
# ~1e-9 .. ~5e9 (covers ns latencies through multi-GiB/s rates).
_BUCKET_HALF_OCTAVES = 128
_BUCKET_OFFSET = 64  # bucket of value 1.0
_DECAY_TICK_S = 5.0  # lazy-decay granularity

# Frames below this feed only the LATENCY estimator: a 2 KiB frame's
# wall time is dispatch overhead, not the wire, and folding it into the
# bandwidth EWMA would drag a 10 GiB/s link toward zero.
BW_MIN_BYTES = 32 * 1024

DEFAULT_HALF_LIFE_S = 120.0
DEFAULT_MAX_LINKS = 512
DEFAULT_ROUND_WINDOW = 32
DEFAULT_STRAGGLER_FACTOR = 0.25
DEFAULT_STRAGGLER_MIN_ROUNDS = 3
# Entry skew below this never flags: scheduler jitter on a loaded box
STRAGGLER_MIN_SKEW_S = 0.002

OTHER = "other"


def perf_dir() -> str:
    """The persistence directory (empty string → persistence off)."""
    return (os.environ.get("FAABRIC_PERF_PROFILE_DIR")
            or os.environ.get("FAABRIC_PERF_DIR") or "")


def size_class(nbytes: int) -> str:
    """Power-of-4 payload class label (the comm-matrix-style cardinality
    trade: 4× resolution keeps a 64 KiB .. 1 GiB span in ~8 classes)."""
    n = max(1, int(nbytes))
    k = (n.bit_length() - 1) // 2
    lo = 1 << (2 * k)
    if lo >= (1 << 30):
        return f"{lo >> 30}GiB"
    if lo >= (1 << 20):
        return f"{lo >> 20}MiB"
    if lo >= (1 << 10):
        return f"{lo >> 10}KiB"
    return f"{lo}B"


def class_floor(label: str) -> int:
    """Inverse of :func:`size_class`: the class's lower bound in bytes
    (0 for anything unparseable)."""
    for suffix, mult in (("GiB", 1 << 30), ("MiB", 1 << 20),
                         ("KiB", 1 << 10), ("B", 1)):
        head = label[:-len(suffix)] if label.endswith(suffix) else ""
        if head.isdigit():
            return int(head) * mult
    return 0


class DecayedStat:
    """Exponentially-decayed streaming estimator: EWMA, decayed mean and
    log-bucket quantiles. NOT thread-safe — the owner serializes (the
    per-link entry holds one lock over its stats)."""

    __slots__ = ("half_life", "ewma", "wsum", "vsum", "counts", "last",
                 "n", "_t_decay")

    def __init__(self, half_life: float) -> None:
        self.half_life = max(1.0, half_life)
        self.ewma = 0.0
        self.wsum = 0.0   # decayed sample weight
        self.vsum = 0.0   # decayed weighted value sum
        self.counts = [0.0] * _BUCKET_HALF_OCTAVES
        self.last = 0.0
        self.n = 0        # raw (undecayed) sample count
        self._t_decay = time.monotonic()

    def _bucket(self, value: float) -> int:
        if value <= 0:
            return 0
        b = int(math.log2(value) * 2.0) + _BUCKET_OFFSET
        return min(max(b, 0), _BUCKET_HALF_OCTAVES - 1)

    def _decay(self, now: float) -> None:
        dt = now - self._t_decay
        if dt < _DECAY_TICK_S:
            return
        f = 0.5 ** (dt / self.half_life)
        self.wsum *= f
        self.vsum *= f
        self.counts = [c * f for c in self.counts]
        self._t_decay = now

    def observe(self, value: float, weight: float = 1.0,
                now: float | None = None) -> None:
        if now is None:
            now = time.monotonic()
        self._decay(now)
        self.n += 1
        self.last = value
        # EWMA warms fast (first samples dominate) then settles at 0.2
        alpha = max(0.2, 1.0 / self.n)
        self.ewma += alpha * (value - self.ewma)
        self.wsum += weight
        self.vsum += weight * value
        self.counts[self._bucket(value)] += weight

    def seed(self, value: float, weight: float = 1.0) -> None:
        """Adopt a persisted estimate as if freshly observed (restart
        seeding): the value is real measurement, just from a previous
        incarnation."""
        self.observe(value, weight)

    @property
    def mean(self) -> float:
        return self.vsum / self.wsum if self.wsum > 0 else 0.0

    @property
    def weight(self) -> float:
        return self.wsum

    def quantile(self, q: float) -> float:
        total = sum(self.counts)
        if total <= 0:
            return 0.0
        target = total * min(max(q, 0.0), 1.0)
        acc = 0.0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= target:
                # geometric bucket midpoint
                return 2.0 ** ((i - _BUCKET_OFFSET) / 2.0 + 0.25)
        return 2.0 ** ((_BUCKET_HALF_OCTAVES - 1 - _BUCKET_OFFSET) / 2.0)


class _LinkEntry:
    """Estimators for one (dst, plane, codec, size-class) link cell.
    Updates take only this entry's lock (comm-matrix discipline)."""

    __slots__ = ("bw", "lat", "bytes", "lat_sum", "messages", "last_wall",
                 "seeded", "_lock")

    def __init__(self, half_life: float) -> None:
        self.bw = DecayedStat(half_life)     # GiB/s per frame
        self.lat = DecayedStat(half_life)    # seconds per frame
        self.bytes = 0.0                     # decay-free totals ride the
        self.lat_sum = 0.0                   # comm matrix; these back
        self.messages = 0                    # the gibs_avg cross-check
        self.last_wall = 0.0
        self.seeded = False
        self._lock = threading.Lock()

    def add(self, nbytes: int, seconds: float | None) -> None:
        with self._lock:
            self.messages += 1
            self.bytes += nbytes
            self.last_wall = time.time()
            if seconds is not None and seconds > 0:
                self.lat.observe(seconds)
                self.lat_sum += seconds
                if nbytes >= BW_MIN_BYTES:
                    self.bw.observe((nbytes / seconds) / (1 << 30))

    def row(self, dst: str, plane: str, codec: str, klass: str) -> dict:
        with self._lock:
            gibs_avg = ((self.bytes / self.lat_sum) / (1 << 30)
                        if self.lat_sum > 0 else None)
            return {
                "dst": dst, "plane": plane, "codec": codec,
                "size_class": klass,
                "messages": self.messages,
                "bytes": int(self.bytes),
                "gibs_ewma": round(self.bw.ewma, 4) if self.bw.n else None,
                "gibs_avg": round(gibs_avg, 4) if gibs_avg else None,
                "gibs_p10": round(self.bw.quantile(0.10), 4),
                "gibs_p50": round(self.bw.quantile(0.50), 4),
                "gibs_p90": round(self.bw.quantile(0.90), 4),
                "lat_p50_ms": round(self.lat.quantile(0.50) * 1e3, 4),
                "lat_p90_ms": round(self.lat.quantile(0.90) * 1e3, 4),
                "weight": round(self.bw.weight, 3),
                "age_s": round(max(0.0, time.time() - self.last_wall), 1)
                if self.last_wall else None,
                "seeded": self.seeded,
            }


class _NullPerfStore:
    """Shared no-op store while metrics / the profile plane is off."""

    __slots__ = ()
    enabled = False

    def observe(self, dst, plane, nbytes, seconds=None,
                codec="raw") -> None:
        pass

    def link_gibs(self, dst, plane=None, min_bytes: int = 0,
                  codec=None):
        # Signature mirrors PerfProfileStore.link_gibs exactly: the
        # schedule selector passes min_bytes, and a metrics-off
        # TypeError here would kill rank 0 before its selection
        # broadcast and hang the world
        return None

    def snapshot(self) -> dict:
        return {}

    def persist(self) -> None:
        pass

    def cardinality(self) -> int:
        return 0


NULL_PERF_STORE = _NullPerfStore()


class PerfProfileStore:
    """Rolling per-link performance profile of THIS process's outbound
    traffic. Keys are (dst host, plane, codec, size-class); the source
    host is implicit (the planner adds it when aggregating, exactly like
    the comm matrix's per-host outbound convention)."""

    # Concurrency contract (tools/concheck.py): registry structures
    # mutate under _lock; per-entry stats under the entry's own lock.
    # NOT listed: _fast — the send-hot-path cache, WRITTEN only under
    # _lock but deliberately read lock-free (GIL-atomic dict.get; a
    # racing reader at worst misses and takes the locked slow path) —
    # the exact CommMatrix._fast discipline.
    GUARDS = {
        "_entries": "_lock",
        "_last_persist": "_lock",
    }

    enabled = True

    def __init__(self, half_life: float | None = None,
                 max_links: int | None = None,
                 label: str | None = None) -> None:
        self.half_life = (half_life if half_life is not None else
                          _env_float("FAABRIC_PERF_HALF_LIFE_S",
                                     DEFAULT_HALF_LIFE_S))
        self.max_links = (max_links if max_links is not None else
                          _env_int("FAABRIC_PERF_MAX_LINKS",
                                   DEFAULT_MAX_LINKS))
        self._label = label
        self._lock = threading.Lock()
        self._entries: dict[tuple, _LinkEntry] = {}
        # Raw (dst, plane, codec, class-index) → entry, read lock-free
        # on the send hot path (one dict hit + one entry add)
        self._fast: dict[tuple, _LinkEntry] = {}
        self._last_persist = 0.0
        self._load_seed()

    # -- hot path -------------------------------------------------------
    def observe(self, dst, plane: str, nbytes: int,
                seconds: float | None = None, codec: str = "raw") -> None:
        klass = size_class(nbytes)
        raw = (dst, plane, codec, klass)
        entry = self._fast.get(raw)
        if entry is None:
            with self._lock:
                # Exact key first: an entry that already exists (e.g.
                # boot-seeded from a persisted profile, which fills
                # _entries but not _fast) must keep receiving live
                # updates even when the store sits at its cap
                entry = self._entries.get(raw)
                if entry is None:
                    key = raw
                    if len(self._entries) >= self.max_links:
                        key = (OTHER, plane, codec, klass)
                    entry = self._entries.get(key)
                    if entry is None:
                        entry = self._entries[key] = _LinkEntry(
                            self.half_life)
                if len(self._fast) >= 8 * self.max_links:
                    # Cardinality backstop mirroring the cap on
                    # _entries: churning destination labels must not
                    # grow the lock-free cache without bound
                    self._fast.clear()
                self._fast[raw] = entry
        entry.add(int(nbytes), seconds)

    # -- queries --------------------------------------------------------
    def link_gibs(self, dst, plane: str | None = None,
                  min_bytes: int = 0,
                  codec: str | None = None) -> float | None:
        """Best current bandwidth estimate toward ``dst`` (max EWMA over
        codecs/size classes with real evidence), or None when the link
        is unmeasured — the governor's assume-slow default then holds.

        ``min_bytes`` drops evidence from size classes below the floor:
        small frames' wall time is dispatch overhead, which reads as a
        falsely slow link — the governor asks for big-frame evidence
        only, so a link carrying nothing but compact delta frames
        reports None (→ fallback) instead of locking itself into
        compression on an underestimate.

        ``codec`` restricts the evidence to one wire codec's rows —
        how the governor's tuned-threshold derivation reads the delta
        path's own measured wire rate (ISSUE 15 satellite)."""
        with self._lock:
            items = list(self._entries.items())
        best = None
        for (d, p, c, klass), e in items:
            if d != dst or (plane is not None and p != plane):
                continue
            if codec is not None and c != codec:
                continue
            if min_bytes and class_floor(klass) < min_bytes:
                continue
            with e._lock:
                if e.bw.n == 0 or e.bw.weight < 0.5:
                    continue
                gibs = e.bw.ewma
            if best is None or gibs > best:
                best = gibs
        return best

    def cardinality(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> dict:
        """JSON-safe wire form riding GET_TELEMETRY's ``perf`` block.
        Opportunistically persists (throttled) — the scrape cadence is
        the natural checkpoint clock."""
        with self._lock:
            items = list(self._entries.items())
        rows = [e.row(d, p, c, k) for (d, p, c, k), e in items]
        rows.sort(key=lambda r: -(r["bytes"] or 0))
        self._maybe_persist()
        return {"links": rows, "half_life_s": self.half_life,
                "max_links": self.max_links}

    # -- persistence ----------------------------------------------------
    def _file_label(self) -> str:
        label = self._label
        if label is None:
            try:
                from faabric_tpu.telemetry.tracer import get_tracer

                label = get_tracer().process_label
            except Exception:  # noqa: BLE001 — label is cosmetic
                label = f"pid-{os.getpid()}"
        return "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in label)

    def _path(self) -> str | None:
        directory = perf_dir()
        if not directory:
            return None
        return os.path.join(directory, f"perf-{self._file_label()}.json")

    def persist(self) -> str | None:
        """Write the current profile (atomic; never raises — a failed
        checkpoint must not take down a send path or a scrape)."""
        path = self._path()
        if path is None:
            return None
        body = {"saved_at": time.time(), "label": self._file_label(),
                "links": self.snapshot_rows_for_persist()}
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(body, f)
            os.replace(tmp, path)
            return path
        except OSError:
            return None

    def snapshot_rows_for_persist(self) -> list[dict]:
        with self._lock:
            items = list(self._entries.items())
        return [e.row(d, p, c, k) for (d, p, c, k), e in items]

    def _maybe_persist(self) -> None:
        if not perf_dir():
            return
        now = time.monotonic()
        interval = _env_float("FAABRIC_PERF_PERSIST_S", 30.0)
        with self._lock:
            if now - self._last_persist < interval:
                return
            self._last_persist = now
        self.persist()

    def _load_seed(self) -> None:
        """Seed estimators from this label's persisted profile: a
        restarted sender starts from measured link speeds (the governor
        keeps its verdicts across restarts) instead of assume-slow."""
        path = self._path()
        if path is None or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                body = json.load(f)
        except (OSError, json.JSONDecodeError):
            return
        for row in body.get("links", []):
            dst, plane = row.get("dst"), row.get("plane")
            codec = row.get("codec", "raw")
            klass = row.get("size_class", "0B")
            if not dst or not plane:
                continue
            with self._lock:
                if len(self._entries) >= self.max_links:
                    return
                key = (dst, plane, codec, klass)
                entry = self._entries.get(key)
                if entry is None:
                    entry = self._entries[key] = _LinkEntry(self.half_life)
            gibs = row.get("gibs_ewma")
            with entry._lock:
                entry.seeded = True
                if isinstance(gibs, (int, float)) and gibs > 0:
                    entry.bw.seed(float(gibs))
                lat = row.get("lat_p50_ms")
                if isinstance(lat, (int, float)) and lat > 0:
                    entry.lat.seed(float(lat) / 1e3)

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()
            self._fast.clear()


# ---------------------------------------------------------------------------
# Collective critical path + straggler detection
# ---------------------------------------------------------------------------

# Phases whose values are absolute wall timestamps, not durations —
# excluded from duration decomposition, used for arrival-skew analysis
TS_PHASES = ("enter_ts",)


class _Series:
    """Rounds of one (world, collective): round idx → rank → phase map.
    Mutated under the owning profiler's lock (record is a few dict ops;
    a shared lock beats per-series locks' creation churn)."""

    __slots__ = ("rounds", "rank_round", "completed", "flagged")

    def __init__(self) -> None:
        self.rounds: dict[int, dict[int, dict[str, float]]] = {}
        self.rank_round: dict[int, int] = {}
        self.completed = 0
        self.flagged: set[int] = set()  # ranks currently flagged


class _NullCollectiveProfiler:
    __slots__ = ()
    enabled = False

    def record_phase(self, world, collective, rank, phase, value,
                     nbytes=0) -> None:
        pass

    def snapshot(self) -> dict:
        return {}

    def detect(self) -> list:
        return []


NULL_COLLECTIVE_PROFILER = _NullCollectiveProfiler()


class CollectiveProfiler:
    """Per-(world, collective, round) phase fold-in + straggler watch.

    ``record_phase(world, collective, rank, phase, value)``: durations
    for named phases (``intra``/``leader``/``redistribute``/``compile``/
    ``execute``), the absolute wall entry stamp as ``enter_ts``, and
    ``total`` — which closes the rank's round and advances its round
    counter. Rounds align across ranks (and, after the planner merge,
    across hosts) because collectives are bulk-synchronous per world:
    every rank's Nth call is the same logical round."""

    GUARDS = {
        "_series": "_lock",
    }

    enabled = True

    def __init__(self, window: int | None = None,
                 factor: float | None = None,
                 min_rounds: int | None = None,
                 max_series: int = 64) -> None:
        self.window = (window if window is not None else
                       _env_int("FAABRIC_PERF_ROUNDS",
                                DEFAULT_ROUND_WINDOW))
        self.factor = (factor if factor is not None else
                       _env_float("FAABRIC_STRAGGLER_FACTOR",
                                  DEFAULT_STRAGGLER_FACTOR))
        self.min_rounds = (min_rounds if min_rounds is not None else
                           _env_int("FAABRIC_STRAGGLER_MIN_ROUNDS",
                                    DEFAULT_STRAGGLER_MIN_ROUNDS))
        self.max_series = max_series
        self._lock = threading.Lock()
        self._series: dict[tuple, _Series] = {}

    def record_phase(self, world, collective: str, rank: int, phase: str,
                     value: float, nbytes: int = 0) -> None:
        key = (world, collective)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                if len(self._series) >= self.max_series:
                    return  # cardinality cap: drop, never grow unbounded
                s = self._series[key] = _Series()
            idx = s.rank_round.get(rank, 0)
            rd = s.rounds.get(idx)
            if rd is None:
                rd = s.rounds[idx] = {}
            phases = rd.get(rank)
            if phases is None:
                phases = rd[rank] = {}
            if phase in TS_PHASES:
                phases[phase] = value  # absolute stamp, last write wins
            else:
                phases[phase] = phases.get(phase, 0.0) + value
            if phase == "total":
                s.rank_round[rank] = idx + 1
                s.completed += 1
                run_detect = s.completed % 16 == 0
                # Prune beyond the window (min over ranks so a lagging
                # rank's round is never dropped under it)
                floor = min(s.rank_round.values()) - self.window
                for old in [i for i in s.rounds if i < floor]:
                    del s.rounds[old]
            else:
                run_detect = False
        if run_detect:
            self._detect_series(world, collective)

    # -- analysis -------------------------------------------------------
    def _detect_series(self, world, collective: str) -> None:
        key = (world, collective)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                return
            rounds = {i: {r: dict(p) for r, p in rd.items()}
                      for i, rd in s.rounds.items()}
            already = set(s.flagged)
        found = find_stragglers(rounds, factor=self.factor,
                                min_rounds=self.min_rounds)
        fresh = {r: st for r, st in found.items() if r not in already}
        with self._lock:
            s = self._series.get(key)
            if s is not None:
                s.flagged = set(found)
        if not fresh:
            return
        from faabric_tpu.telemetry.flight import flight_record
        from faabric_tpu.telemetry.tracer import instant

        metrics = get_metrics()
        for rank, st in fresh.items():
            metrics.counter(
                "faabric_straggler_detected_total",
                "Ranks newly flagged as consistently late arrivers",
                world=world, collective=collective, rank=rank).inc()
            metrics.gauge(
                "faabric_straggler_skew_seconds",
                "Last detected median entry skew of a flagged rank",
                world=world, collective=collective,
                rank=rank).set(st["median_skew_s"])
            flight_record("straggler", world=world, collective=collective,
                          rank=rank, skew_s=round(st["median_skew_s"], 6),
                          rounds=st["rounds_flagged"])
            instant("perf", "straggler", world=world,
                    collective=collective, rank=rank,
                    skew_ms=round(st["median_skew_s"] * 1e3, 3))

    def detect(self) -> list[dict]:
        """Run detection over every series; returns the current flags
        (also refreshes metrics/flight on fresh detections)."""
        with self._lock:
            keys = list(self._series)
        for world, collective in keys:
            self._detect_series(world, collective)
        out = []
        with self._lock:
            for (world, collective), s in self._series.items():
                for rank in sorted(s.flagged):
                    out.append({"world": world, "collective": collective,
                                "rank": rank})
        return out

    def snapshot(self) -> list[dict]:
        """JSON-safe series dump (round maps keyed by stringified ints
        for the wire) + per-series critical path and straggler flags."""
        self.detect()
        with self._lock:
            items = [((w, c), {i: {r: dict(p) for r, p in rd.items()}
                               for i, rd in s.rounds.items()},
                      sorted(s.flagged), s.completed)
                     for (w, c), s in self._series.items()]
        out = []
        for (world, collective), rounds, flagged, completed in items:
            out.append({
                "world": world,
                "collective": collective,
                "completed": completed,
                "rounds": {str(i): {str(r): {k: round(v, 6)
                                             for k, v in p.items()}
                                    for r, p in rd.items()}
                           for i, rd in rounds.items()},
                "stragglers": flagged,
                "critical_path": critical_path(rounds),
            })
        return out

    def reset(self) -> None:
        with self._lock:
            self._series.clear()


# ---------------------------------------------------------------------------
# Pure analysis + merge helpers (planner aggregation and the doctor)
# ---------------------------------------------------------------------------

def _round_items(rounds: dict) -> list[tuple[int, dict[int, dict]]]:
    """Normalize a rounds map whose keys may be ints (in-process) or
    strings (JSON round-trip) into sorted (idx, {rank: phases})."""
    out = []
    for i, rd in rounds.items():
        ranks = {int(r): p for r, p in rd.items()}
        out.append((int(i), ranks))
    out.sort()
    return out


def _median(values: list[float]) -> float:
    vs = sorted(values)
    n = len(vs)
    if n == 0:
        return 0.0
    mid = n // 2
    return vs[mid] if n % 2 else 0.5 * (vs[mid - 1] + vs[mid])


def find_stragglers(rounds: dict, factor: float = DEFAULT_STRAGGLER_FACTOR,
                    min_rounds: int = DEFAULT_STRAGGLER_MIN_ROUNDS,
                    min_skew_s: float = STRAGGLER_MIN_SKEW_S) -> dict:
    """Ranks that consistently ARRIVE late of their own accord.

    The signal is the **inter-round idle gap**: ``enter(k) −
    (enter(k−1) + total(k−1))`` — how long the rank sat OUTSIDE the
    collective between rounds — compared to the round's median gap. A
    rank is flagged when its gap excess beats ``max(min_skew_s,
    factor × median round total)`` in ≥ ``min_rounds`` round pairs AND
    at least half the pairs it appears in.

    Why the gap and not raw entry stamps or totals:

    - *totals* cannot identify a straggler — a synchronous collective's
      late arriver inflates every rank's total equally;
    - *raw entry skew* has two failure modes: cross-host wall-clock
      offset reads as a whole host arriving "late", and the straggler's
      lateness ECHOES through the data-dependency structure (a ring
      successor stuck waiting inside round k−1 also *enters* round k
      late, through no fault of its own).

    The gap dodges both: it subtracts two stamps taken on the SAME
    rank's clock (host offsets cancel exactly — ``total`` is a
    duration), and an echo victim's delay is spent *inside* the
    previous collective, so its idle gap stays ~zero while the true
    straggler's pre-collective dawdling is exactly the gap.

    Returns ``{rank: {"rounds_flagged", "rounds_seen",
    "median_skew_s"}}`` (``median_skew_s`` = median excess idle gap)."""
    items = _round_items(rounds)
    by_idx = dict(items)
    seen: dict[int, int] = {}
    flagged: dict[int, int] = {}
    skews: dict[int, list[float]] = {}
    for idx, ranks in items:
        prev = by_idx.get(idx - 1)
        if prev is None:
            continue  # first round (or a pruned gap): no pair
        gaps = {}
        for r, p in ranks.items():
            pp = prev.get(r)
            if ("enter_ts" in p and pp is not None
                    and "enter_ts" in pp and pp.get("total")):
                gaps[r] = p["enter_ts"] - (pp["enter_ts"] + pp["total"])
        if len(gaps) < 2:
            continue
        med_gap = _median(list(gaps.values()))
        totals = [p.get("total", 0.0) for p in ranks.values()
                  if p.get("total")]
        threshold = max(min_skew_s,
                        factor * _median(totals) if totals else 0.0)
        for r, g in gaps.items():
            seen[r] = seen.get(r, 0) + 1
            skew = g - med_gap
            skews.setdefault(r, []).append(skew)
            if skew > threshold:
                flagged[r] = flagged.get(r, 0) + 1
    out = {}
    for r, n_flag in flagged.items():
        if n_flag >= min_rounds and n_flag * 2 >= seen.get(r, 0):
            out[r] = {"rounds_flagged": n_flag,
                      "rounds_seen": seen.get(r, 0),
                      "median_skew_s": _median(skews.get(r, [0.0]))}
    return out


def critical_path(rounds: dict) -> dict:
    """Which rank/phase bounded the rounds: per round the rank with the
    largest total is the bound; its phase durations decompose the round.
    Returns aggregate counts plus the dominant (rank, phase)."""
    items = _round_items(rounds)
    bound_counts: dict[int, int] = {}
    phase_time: dict[str, float] = {}
    analyzed = 0
    for _idx, ranks in items:
        totals = {r: p.get("total") for r, p in ranks.items()
                  if p.get("total")}
        if not totals:
            continue
        analyzed += 1
        bound = max(totals, key=lambda r: totals[r])
        bound_counts[bound] = bound_counts.get(bound, 0) + 1
        for phase, v in ranks[bound].items():
            if phase in TS_PHASES or phase == "total":
                continue
            phase_time[phase] = phase_time.get(phase, 0.0) + v
    total_phase = sum(phase_time.values())
    shares = ({p: round(v / total_phase, 4)
               for p, v in sorted(phase_time.items(),
                                  key=lambda kv: -kv[1])}
              if total_phase > 0 else {})
    dominant_rank = (max(bound_counts, key=lambda r: bound_counts[r])
                     if bound_counts else None)
    dominant_phase = next(iter(shares), None)
    return {"rounds_analyzed": analyzed,
            "bound_counts": {str(r): c for r, c in
                             sorted(bound_counts.items())},
            "phase_shares": shares,
            "dominant_rank": dominant_rank,
            "dominant_phase": dominant_phase}


def merge_link_rows(per_host: dict[str, list[dict]]) -> list[dict]:
    """Tag each host's outbound profile rows with their source host —
    the cluster-wide (src, dst, plane, codec, size-class) link table.
    Hosts only report their own outbound links, so this is a pure
    union, never a sum."""
    out = []
    for host, rows in per_host.items():
        for r in rows or []:
            out.append({"src": host, **r})
    out.sort(key=lambda r: -(r.get("bytes") or 0))
    return out


def merge_collective_series(per_host: dict[str, list[dict]]) -> list[dict]:
    """Union hosts' (world, collective) series: each host recorded its
    own ranks' phases, and rounds align by index (collectives are
    bulk-synchronous), so merging is a per-round rank-map union. The
    merged series re-runs critical-path and straggler analysis — this
    is where a dist world's cross-host comparison becomes possible."""
    merged: dict[tuple, dict] = {}
    for host, series in per_host.items():
        for s in series or []:
            key = (s.get("world"), s.get("collective"))
            m = merged.get(key)
            if m is None:
                m = merged[key] = {"world": s.get("world"),
                                   "collective": s.get("collective"),
                                   "completed": 0, "rounds": {},
                                   "rank_hosts": {},
                                   "stragglers_local": set()}
            m["completed"] += s.get("completed", 0)
            m["stragglers_local"].update(s.get("stragglers") or [])
            for idx, ranks in (s.get("rounds") or {}).items():
                rd = m["rounds"].setdefault(str(idx), {})
                for r, phases in ranks.items():
                    rd.setdefault(str(r), {}).update(phases)
                    # Provenance IS placement: the host whose series
                    # carried this rank's phases executed that rank
                    m["rank_hosts"][str(r)] = host
    out = []
    for m in merged.values():
        rounds = m["rounds"]
        stragglers = find_stragglers(rounds)
        out.append({
            "world": m["world"], "collective": m["collective"],
            "completed": m["completed"],
            "rounds": rounds,
            "rank_hosts": m["rank_hosts"],
            "critical_path": critical_path(rounds),
            "stragglers": {str(r): st for r, st in stragglers.items()},
            "stragglers_local": sorted(m["stragglers_local"]),
        })
    out.sort(key=lambda s: -(s.get("completed") or 0))
    return out


def aggregate_perf(tel: dict) -> dict:
    """The cluster-wide ``GET /perf`` document from a
    ``collect_telemetry()`` result: per-host profile blocks merged into
    one link table + merged collective series with cross-host straggler
    analysis."""
    link_rows: dict[str, list[dict]] = {}
    coll: dict[str, list[dict]] = {}
    for host, t in tel.items():
        perf = (t or {}).get("perf") or {}
        link_rows[host] = (perf.get("links") or {}).get("links") or []
        coll[host] = perf.get("collectives") or []
    collectives = merge_collective_series(coll)
    stragglers = []
    for s in collectives:
        for rank, st in (s.get("stragglers") or {}).items():
            stragglers.append({"world": s["world"],
                               "collective": s["collective"],
                               "rank": int(rank),
                               "host": (s.get("rank_hosts") or {}).get(
                                   str(rank)), **st})
    stragglers.sort(key=lambda s: -s.get("median_skew_s", 0.0))
    return {
        "generated_at": time.time(),
        "links": merge_link_rows(link_rows),
        "collectives": collectives,
        "stragglers": stragglers,
        "hosts": sorted(link_rows),
    }


def persist_cluster(doc: dict) -> str | None:
    """Checkpoint the aggregated cluster view (atomic, best-effort) so
    the doctor — and the next planner incarnation — can read the last
    known cluster profile without a live scrape."""
    directory = perf_dir()
    if not directory:
        return None
    path = os.path.join(directory, "perf-cluster.json")
    try:
        os.makedirs(directory, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path
    except OSError:
        return None


# ---------------------------------------------------------------------------
# Singletons
# ---------------------------------------------------------------------------

def _plane_enabled() -> bool:
    return (metrics_enabled()
            and os.environ.get("FAABRIC_PERF_PROFILE", "1")
            not in ("0", "false", "off"))


_store: PerfProfileStore | None = None
_profiler: CollectiveProfiler | None = None
_singleton_lock = threading.Lock()


def get_perf_store() -> PerfProfileStore | _NullPerfStore:
    if not _plane_enabled():
        return NULL_PERF_STORE
    global _store
    if _store is None:
        with _singleton_lock:
            if _store is None:
                _store = PerfProfileStore()
    return _store


def get_collective_profiler() -> CollectiveProfiler | _NullCollectiveProfiler:
    if not _plane_enabled():
        return NULL_COLLECTIVE_PROFILER
    global _profiler
    if _profiler is None:
        with _singleton_lock:
            if _profiler is None:
                _profiler = CollectiveProfiler()
    return _profiler


def perf_telemetry_block() -> dict:
    """The ``perf`` block riding GET_TELEMETRY (and the planner's own
    entry): this process's link profiles + collective series."""
    store = get_perf_store()
    profiler = get_collective_profiler()
    if not store.enabled and not profiler.enabled:
        return {}
    return {"links": store.snapshot(),
            "collectives": profiler.snapshot()}


def reset_perf_profile() -> None:
    """Test hook: drop both singletons so the next use re-reads env."""
    global _store, _profiler
    with _singleton_lock:
        _store = None
        _profiler = None
