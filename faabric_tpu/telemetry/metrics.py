"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

Design points (HiCCL-style phase accounting needs cheap always-on
counters; EQuARX-style reports need histograms):

- **Lock-sharded**: every series (one metric name + one label set) owns
  its own ``threading.Lock``; the registry-level lock is taken only when
  a handle is *created*. Hot paths hold module-level handles, so steady
  state is one uncontended per-series lock per update.
- **Near-zero cost when disabled** (``FAABRIC_METRICS=0``): handle
  creation returns a single shared no-op object, so every ``inc``/
  ``observe`` is one attribute call on a singleton — no allocation, no
  locking, no branching in the caller.
- **Typed handles**: ``Counter`` (monotonic), ``Gauge`` (set/inc/dec)
  and ``Histogram`` (fixed upper bounds, cumulative render). Re-asking
  for a name with a different type raises — a registry that silently
  aliases types produces unparseable exposition output.

Export surfaces: ``render_prometheus`` (text exposition format, served
by the planner's ``GET /metrics``), ``snapshot`` (JSON-safe dict that
rides the GET_TELEMETRY RPC from workers to the planner), and
``render_snapshots`` (merges many hosts' snapshots under a ``host``
label).
"""

from __future__ import annotations

import math
import os
import threading
from typing import Optional

# Default latency buckets (seconds) — spans RPC dispatch (~100 µs) to a
# wedged collective (~10 s); same shape as the prometheus client default.
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

_enabled = os.environ.get("FAABRIC_METRICS", "1") not in ("0", "false", "off")


def metrics_enabled() -> bool:
    return _enabled


def set_metrics_enabled(on: bool) -> None:
    """Test hook; production processes decide at boot via FAABRIC_METRICS.
    Handles already held by callers keep their behaviour — only handles
    created after the flip observe the new state."""
    global _enabled
    _enabled = on


class _NullMetric:
    """Shared no-op handle returned while metrics are disabled."""

    __slots__ = ()

    def inc(self, value: float = 1.0) -> None:
        pass

    def dec(self, value: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass


NULL_METRIC = _NullMetric()


class Counter:
    __slots__ = ("labels", "_lock", "value")

    def __init__(self, labels: dict[str, str]) -> None:
        self.labels = labels
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, value: float = 1.0) -> None:
        if value < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self.value += value


class Gauge:
    __slots__ = ("labels", "_lock", "value")

    def __init__(self, labels: dict[str, str]) -> None:
        self.labels = labels
        self._lock = threading.Lock()
        self.value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, value: float = 1.0) -> None:
        with self._lock:
            self.value += value

    def dec(self, value: float = 1.0) -> None:
        with self._lock:
            self.value -= value


class Histogram:
    __slots__ = ("labels", "buckets", "_lock", "counts", "sum", "count")

    def __init__(self, labels: dict[str, str],
                 buckets: tuple[float, ...]) -> None:
        self.labels = labels
        self.buckets = buckets  # finite upper bounds, ascending
        self._lock = threading.Lock()
        self.counts = [0] * len(buckets)  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        # Bisect outside the lock: buckets are immutable
        lo, hi = 0, len(self.buckets)
        while lo < hi:
            mid = (lo + hi) // 2
            if value <= self.buckets[mid]:
                hi = mid
            else:
                lo = mid + 1
        with self._lock:
            if lo < len(self.counts):
                self.counts[lo] += 1
            self.sum += value
            self.count += 1


class _Family:
    __slots__ = ("name", "type", "help", "buckets", "series")

    def __init__(self, name: str, mtype: str, help_: str,
                 buckets: Optional[tuple[float, ...]]) -> None:
        self.name = name
        self.type = mtype
        self.help = help_
        self.buckets = buckets
        # label-tuple → handle
        self.series: dict[tuple, object] = {}


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    # -- handle creation ------------------------------------------------
    def counter(self, name: str, help: str = "", **labels: str):
        return self._get(name, "counter", help, None, labels)

    def gauge(self, name: str, help: str = "", **labels: str):
        return self._get(name, "gauge", help, None, labels)

    def histogram(self, name: str, help: str = "",
                  buckets: tuple[float, ...] = DEFAULT_BUCKETS,
                  **labels: str):
        return self._get(name, "histogram", help, tuple(buckets), labels)

    def _get(self, name: str, mtype: str, help_: str,
             buckets: Optional[tuple[float, ...]], labels: dict):
        if not _enabled:
            return NULL_METRIC
        labels = {k: str(v) for k, v in labels.items()}
        key = tuple(sorted(labels.items()))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, mtype, help_, buckets)
                self._families[name] = fam
            elif fam.type != mtype:
                raise ValueError(
                    f"metric {name} already registered as {fam.type}")
            handle = fam.series.get(key)
            if handle is None:
                if mtype == "counter":
                    handle = Counter(labels)
                elif mtype == "gauge":
                    handle = Gauge(labels)
                else:
                    handle = Histogram(labels, fam.buckets or DEFAULT_BUCKETS)
                fam.series[key] = handle
            return handle

    # -- export ---------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-safe dump: the wire form workers ship to the planner."""
        out: dict = {}
        with self._lock:
            families = [(f.name, f.type, f.help, list(f.series.values()))
                        for f in self._families.values()]
        for name, mtype, help_, series in families:
            rows = []
            for s in series:
                with s._lock:
                    if mtype == "histogram":
                        rows.append({
                            "labels": dict(s.labels),
                            "sum": s.sum, "count": s.count,
                            "buckets": [[b, c] for b, c in
                                        zip(s.buckets, s.counts)],
                        })
                    else:
                        rows.append({"labels": dict(s.labels),
                                     "value": s.value})
            out[name] = {"type": mtype, "help": help_, "series": rows}
        return out

    def render_prometheus(self, extra_labels: dict[str, str] | None = None
                          ) -> str:
        return render_snapshots({None: self.snapshot()},
                                extra_labels=extra_labels)

    def reset(self) -> None:
        with self._lock:
            self._families.clear()


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _label_str(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def render_snapshots(snapshots: dict, extra_labels: dict | None = None
                     ) -> str:
    """Prometheus text exposition from one or more ``snapshot()`` dumps.

    ``snapshots`` maps a host label value (or None for no host label) to
    a snapshot; every series of host ``h`` is rendered with ``host="h"``
    added, which is how the planner merges all workers' local registries
    into one scrape page."""
    # Merge family metadata across hosts (HELP/TYPE must appear once)
    merged: dict[str, dict] = {}
    for host, snap in snapshots.items():
        for name, fam in (snap or {}).items():
            m = merged.setdefault(name, {"type": fam.get("type", "counter"),
                                         "help": fam.get("help", ""),
                                         "rows": []})
            for row in fam.get("series", []):
                labels = dict(row.get("labels", {}))
                if host is not None:
                    labels["host"] = str(host)
                if extra_labels:
                    labels.update(extra_labels)
                m["rows"].append((labels, row))
    lines: list[str] = []
    for name in sorted(merged):
        fam = merged[name]
        if fam["help"]:
            lines.append(f"# HELP {name} {fam['help']}")
        lines.append(f"# TYPE {name} {fam['type']}")
        for labels, row in fam["rows"]:
            if fam["type"] == "histogram":
                cum = 0
                for le, c in row.get("buckets", []):
                    cum += c
                    bl = dict(labels)
                    bl["le"] = _fmt(le)
                    lines.append(f"{name}_bucket{_label_str(bl)} {cum}")
                bl = dict(labels)
                bl["le"] = "+Inf"
                lines.append(
                    f"{name}_bucket{_label_str(bl)} {row.get('count', 0)}")
                lines.append(
                    f"{name}_sum{_label_str(labels)} {row.get('sum', 0.0)}")
                lines.append(
                    f"{name}_count{_label_str(labels)} {row.get('count', 0)}")
            else:
                lines.append(
                    f"{name}{_label_str(labels)} {row.get('value', 0.0)}")
    return "\n".join(lines) + "\n"


def snapshot_delta(before: dict, after: dict) -> dict:
    """Flat ``{"name{labels}": delta}`` of counter increments and
    histogram sum/count growth between two snapshots — what bench.py
    writes per section so rounds get per-phase traffic trajectories."""
    out: dict[str, float] = {}

    def _index(snap):
        idx = {}
        for name, fam in (snap or {}).items():
            for row in fam.get("series", []):
                key = name + _label_str(row.get("labels", {}))
                idx[key] = (fam.get("type"), row)
        return idx

    b, a = _index(before), _index(after)
    for key, (mtype, row) in a.items():
        prev = b.get(key, (mtype, None))[1]
        if mtype == "histogram":
            ds = row.get("sum", 0.0) - (prev.get("sum", 0.0) if prev else 0.0)
            dc = row.get("count", 0) - (prev.get("count", 0) if prev else 0)
            if dc:
                out[key + "_sum"] = round(ds, 6)
                out[key + "_count"] = dc
        else:
            dv = row.get("value", 0.0) - (prev.get("value", 0.0)
                                          if prev else 0.0)
            if dv:
                out[key] = round(dv, 6)
    return out


_registry: MetricsRegistry | None = None
_registry_lock = threading.Lock()


def get_metrics() -> MetricsRegistry:
    global _registry
    if _registry is None:
        with _registry_lock:
            if _registry is None:
                _registry = MetricsRegistry()
    return _registry
