"""Post-mortem flight recorder: a fixed-size ring of recent telemetry
events per process, dumped to disk when something dies.

The black-box model: every process continuously records compact events
(remote sends, fault firings, group aborts, requeues, executor
exceptions, host expiry) into a PREALLOCATED ring — a plain Python list
whose slots are overwritten in arrival order. Slot assignment rides an
``itertools.count`` (GIL-atomic) and each record is one tuple + one
small dict, no locks on the hot path, so the recorder is cheap enough to
stay on by default. When a terminal condition fires (``MpiWorldAborted``
→ the broker's group abort, a planner requeue, an unhandled executor
exception, SIGTERM), the ring is serialized to ``FAABRIC_FLIGHT_DIR`` as
one JSON file per process; ``python -m faabric_tpu.runner.flightdump``
merges the files from every host onto one wall-clock timeline.

Knobs:

- ``FAABRIC_FLIGHT``       — ``0`` disables recording entirely (shared
  no-op handle; a ``record()`` is then one no-op method call).
- ``FAABRIC_FLIGHT_RING``  — ring length (default 4096 events).
- ``FAABRIC_FLIGHT_DIR``   — dump directory. Unset → dumps are skipped
  (the ring still records, so a debugger can read it in-process).

Timestamps are wall-clock-anchored (``wall_epoch + monotonic_delta``),
the same convention as the span tracer, so rings dumped by different
hosts merge onto one timeline.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time

_DUMP_THROTTLE_SECONDS = 1.0


class _NullFlight:
    """Shared no-op recorder returned while flight recording is off."""

    __slots__ = ()
    size = 0

    def record(self, kind: str, **fields) -> None:
        pass

    def events(self) -> list:
        return []

    def dump(self, reason: str):
        return None


NULL_FLIGHT = _NullFlight()


class FlightRecorder:
    """Fixed-size overwrite-oldest event ring.

    ``record`` is the hot path: one counter draw (GIL-atomic), one tuple
    build, one list-slot store. No lock — a torn read in ``events()``
    (a slot overwritten mid-snapshot) can at worst misorder one event at
    the ring seam, which a post-mortem reader sorts by timestamp anyway.
    """

    def __init__(self, size: int = 4096) -> None:
        self.size = max(8, int(size))
        self._slots: list = [None] * self.size
        self._n = itertools.count()
        self._count = 0  # advisory; exact value comes from the counter
        # Wall anchor shared with the tracer's convention so merged
        # dumps and merged traces line up
        self._wall0 = time.time() - time.monotonic()
        self._last_dump: dict[str, float] = {}
        self._dump_lock = threading.Lock()

    # -- recording ------------------------------------------------------
    def record(self, kind: str, **fields) -> None:
        i = next(self._n)
        self._slots[i % self.size] = (
            self._wall0 + time.monotonic(), i, kind, fields)
        self._count = i + 1

    def events(self) -> list[dict]:
        """Snapshot, oldest → newest. Entries are
        ``{"ts", "seq", "kind", ...fields}``."""
        n = self._count
        slots = list(self._slots)  # one pass; racers overwrite harmlessly
        live = [s for s in slots if s is not None]
        live.sort(key=lambda s: s[1])  # seq order handles the ring seam
        if n > self.size:
            live = live[-self.size:]
        return [{"ts": ts, "seq": seq, "kind": kind, **fields}
                for ts, seq, kind, fields in live]

    # -- dumping --------------------------------------------------------
    def dump(self, reason: str):
        """Serialize the ring to ``FAABRIC_FLIGHT_DIR`` (one file per
        process per trigger); returns the path or None when dumping is
        disabled/throttled. Never raises — a failing dump must not mask
        the failure being recorded."""
        directory = os.environ.get("FAABRIC_FLIGHT_DIR", "")
        if not directory:
            return None
        now = time.monotonic()
        with self._dump_lock:
            if now - self._last_dump.get(reason, -1e9) < \
                    _DUMP_THROTTLE_SECONDS:
                return None
            self._last_dump[reason] = now
        try:
            from faabric_tpu.telemetry.tracer import get_tracer

            label = get_tracer().process_label
        except Exception:  # noqa: BLE001 — label is cosmetic
            label = f"pid-{os.getpid()}"
        safe_label = "".join(c if c.isalnum() or c in "-_." else "_"
                             for c in label)
        path = os.path.join(
            directory,
            f"flight-{safe_label}-{os.getpid()}-{time.time_ns()}.json")
        body = {
            "process": label,
            "pid": os.getpid(),
            "reason": reason,
            "dumped_at": time.time(),
            "ring_size": self.size,
            "events_recorded": self._count,
            "events": self.events(),
        }
        try:
            os.makedirs(directory, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(body, f, default=str)
            os.replace(tmp, path)
            self._prune_own_dumps(directory)
            return path
        except OSError:
            return None

    @staticmethod
    def _prune_own_dumps(directory: str) -> None:
        """Keep at most FAABRIC_FLIGHT_MAX_DUMPS (default 20) of THIS
        process's dump files: a recurring trigger (a guest function that
        always raises, a recovery loop) must not fill the disk. Only
        own-pid files are pruned — other processes' black boxes are
        theirs to manage."""
        try:
            keep = int(os.environ.get("FAABRIC_FLIGHT_MAX_DUMPS", 20))
        except ValueError:
            keep = 20
        marker = f"-{os.getpid()}-"
        try:
            mine = sorted(n for n in os.listdir(directory)
                          if n.startswith("flight-") and marker in n
                          and n.endswith(".json"))
        except OSError:
            return
        for name in mine[:-keep] if keep > 0 else mine:
            try:
                os.remove(os.path.join(directory, name))
            except OSError:
                pass


def _env_enabled() -> bool:
    return os.environ.get("FAABRIC_FLIGHT", "1") not in ("0", "false", "off")


_flight: FlightRecorder | _NullFlight | None = None
_flight_lock = threading.Lock()


def get_flight() -> FlightRecorder | _NullFlight:
    global _flight
    if _flight is None:
        with _flight_lock:
            if _flight is None:
                if _env_enabled():
                    try:
                        size = int(os.environ.get("FAABRIC_FLIGHT_RING",
                                                  4096))
                    except ValueError:
                        # A malformed knob must degrade to the default,
                        # never fail the send/recovery paths that call
                        # flight_record()
                        size = 4096
                    _flight = FlightRecorder(size)
                else:
                    _flight = NULL_FLIGHT
    return _flight


# -- module-level conveniences (instrumentation sites hold these) -------
def flight_record(kind: str, **fields) -> None:
    get_flight().record(kind, **fields)


def live_ring_doc() -> dict:
    """This process's LIVE ring as one JSON-safe document — the body
    both the planner and worker HTTP endpoints serve at ``GET /flight``
    (one schema, one place; ``flightdump --url`` merges on it)."""
    try:
        from faabric_tpu.telemetry.tracer import get_tracer

        label = get_tracer().process_label
    except Exception:  # noqa: BLE001 — label is cosmetic
        label = f"pid-{os.getpid()}"
    ring = get_flight()
    return {
        "process": label,
        "pid": os.getpid(),
        "ring_size": ring.size,
        "events": ring.events(),
    }


def flight_dump(reason: str):
    return get_flight().dump(reason)


def install_signal_dump() -> None:
    """Chain a SIGTERM handler that dumps the ring, then replicates the
    PREVIOUS disposition exactly: a prior handler runs, SIG_IGN stays
    ignored, and SIG_DFL re-raises through the default action so the
    process still dies with the signal (exit status 143, not a fake
    clean 0 — supervisors distinguish the two). Main-thread only;
    silently skipped elsewhere."""
    import signal

    try:
        prev = signal.getsignal(signal.SIGTERM)

        def handler(signum, frame):
            try:
                flight_record("sigterm", pid=os.getpid())
                flight_dump("sigterm")
            except Exception:  # noqa: BLE001 — never mask the signal
                pass
            if prev is signal.SIG_IGN:
                return
            if callable(prev) and prev not in (signal.SIG_IGN,
                                               signal.SIG_DFL):
                prev(signum, frame)
                return
            # Default disposition: restore it and re-raise the signal
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

        signal.signal(signal.SIGTERM, handler)
    except ValueError:
        pass  # not the main thread
