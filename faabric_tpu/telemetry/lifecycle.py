"""Invocation lifecycle plane (ISSUE 14): where do an invocation's
milliseconds go?

PRs 1/3/12 made the *data* plane legible; the control plane — the PR 8
ingress path about to be sharded for 10k+ inv/s — still exposed only
point-in-time counters. faabric itself stamps a per-message ledger
(exec-graph nodes carry queue/exec/wall ms); this module reproduces it
end to end, cluster-merged:

- **Phase ledger**: every Message carries a compact ``lc`` dict of
  monotonic nanosecond stamps (short wire keys, see ``PHASE_LABELS``)
  written at admit, ingress-queue exit, tick schedule, journal append,
  dispatch send, executor-queue exit, run start/end, result push,
  planner record and waiter wake — across processes, because the dict
  rides the Message wire form (``to_wire_dict``) on dispatch and on the
  result push. Recovery requeues stamp a ``requeue`` boundary, so a
  message that died with its host carries a ledger spanning BOTH
  attempts. Stamps are ``time.monotonic_ns()``: on one machine (every
  process shares CLOCK_MONOTONIC) all stamps compare exactly; across
  real machines the two transit phases (``executor_queue``, ``record``)
  absorb the clock offset — the same honesty caveat as
  ``faabric_planner_result_roundtrip_seconds``.
- **Fold**: when the planner records a result, the ledger folds into
  per-phase log-bucket streaming estimators (the perfprofile
  ``DecayedStat``) plus an end-to-end digest — served on ``/healthz``
  (``lifecycle`` block: per-phase quantiles + the dominant-phase
  ranking the doctor reads) and ``/metrics``
  (``faabric_lifecycle_phase_seconds``/``faabric_lifecycle_e2e_seconds``
  histograms).
- **SLO tracker**: declared targets (``FAABRIC_SLO``, e.g.
  ``p99_e2e_ms=50,error_rate=0.001``) evaluated with multi-window burn
  rates over time-bucketed counters; burn onset is flight-recorded and
  the rates ride ``/healthz`` + ``/metrics``.

Cost contract: one stamp is one dict store + one ``monotonic_ns`` call
(~100 ns, benched as ``lifecycle_stamp_ns``); with ``FAABRIC_METRICS=0``
(or ``FAABRIC_LIFECYCLE=0``) every handle is the shared no-op singleton
— ``get_lifecycle() is NULL_LIFECYCLE`` — so the stamping sites cost
one no-op method call and the wire dict carries an empty ``lc``.

Knobs: ``FAABRIC_LIFECYCLE`` (default on while metrics are on),
``FAABRIC_SLO`` (spec; empty → tracker off), ``FAABRIC_SLO_WINDOWS``
(comma seconds, default ``60,600``), ``FAABRIC_SLO_BURN`` (burn-rate
threshold, default 2.0), ``FAABRIC_SLO_BUCKET_S`` (counter bucket
width, default 5), ``FAABRIC_SLO_MIN_COUNT`` (evidence floor per
window, default 20).
"""

from __future__ import annotations

import os
import threading
import time

from faabric_tpu.telemetry.metrics import get_metrics, metrics_enabled
from faabric_tpu.telemetry.perfprofile import DecayedStat
from faabric_tpu.util.config import _env_float, _env_int

# -- phase taxonomy -----------------------------------------------------
# Wire keys are short on purpose: the ledger rides EVERY dispatched and
# result-pushed message's JSON header. Values are monotonic ns stamps.
PHASE_ADMIT = "adm"            # admission granted / classic entry
PHASE_QUEUE_EXIT = "qex"       # left the ingress queue (tick pickup)
PHASE_SCHED = "sch"            # scheduling decision made
PHASE_JOURNAL = "jnl"          # journal append done
PHASE_DISPATCH = "dsp"         # dispatch RPC about to be written
PHASE_REQUEUE = "rqu"          # recovery requeue boundary
PHASE_EXEC_QUEUE_EXIT = "eqx"  # executor pool thread picked the task
PHASE_RUN_START = "rns"        # guest execute_task entered
PHASE_RUN_END = "rne"          # guest execute_task returned
PHASE_RESULT_PUSH = "rsp"      # worker pushing the result
PHASE_RECORDED = "rec"         # planner recorded the result
PHASE_WAITER_WAKE = "wwk"      # waiting client woken with the result

# NOT a stamp: accumulated in-run state pull/push nanoseconds (ISSUE
# 16). Written by charge_state_time() from the state hot paths while an
# ExecutorContext is set; ledger_durations() carves it out of the run
# window as its own "state" phase so /healthz dominant-phase ranking
# can attribute state-bound invocations (they used to read as opaque
# "run"). A duration key must never enter the time-sorted stamp walk —
# its value is an interval, not a point on the monotonic clock.
PHASE_STATE_ACC = "stx"

# Duration label for the gap ENDING at each stamp (time-sorted — a
# requeued message's second-attempt dispatch stamp lands after its
# requeue stamp, and the sort attributes the gaps truthfully).
PHASE_LABELS = {
    PHASE_QUEUE_EXIT: "ingress_queue",
    PHASE_SCHED: "schedule",
    PHASE_JOURNAL: "journal",
    PHASE_DISPATCH: "dispatch",
    PHASE_REQUEUE: "requeue",
    PHASE_EXEC_QUEUE_EXIT: "executor_queue",
    PHASE_RUN_START: "run_prep",
    PHASE_RUN_END: "run",
    PHASE_RESULT_PUSH: "result_push",
    PHASE_RECORDED: "record",
    PHASE_WAITER_WAKE: "waiter_wake",
}


def lifecycle_enabled() -> bool:
    return (metrics_enabled()
            and os.environ.get("FAABRIC_LIFECYCLE", "1")
            not in ("0", "false", "off"))


class _NullLifecycle:
    """Shared no-op stamper while the plane is off: identity-checkable
    (``get_lifecycle() is NULL_LIFECYCLE``) so the disabled path is one
    no-op method call per site."""

    __slots__ = ()
    enabled = False

    def stamp(self, msg, phase: str) -> None:
        pass

    def stamp_first(self, msg, phase: str) -> None:
        pass

    def stamp_many(self, msgs, phase: str) -> None:
        pass


NULL_LIFECYCLE = _NullLifecycle()


class Lifecycle:
    """The stamper. Stateless — stamps live on the Message itself so
    they travel the wire; no locking (each message is stamped by the
    one thread currently owning its lifecycle step)."""

    __slots__ = ()
    enabled = True

    @staticmethod
    def stamp(msg, phase: str) -> None:
        msg.lc[phase] = time.monotonic_ns()

    @staticmethod
    def stamp_first(msg, phase: str) -> None:
        """First-write stamp: ``admit`` must survive re-entries (thaw,
        direct call_batch after an ingress stamp)."""
        if phase not in msg.lc:
            msg.lc[phase] = time.monotonic_ns()

    @staticmethod
    def stamp_many(msgs, phase: str) -> None:
        now = time.monotonic_ns()
        for m in msgs:
            m.lc[phase] = now


_lifecycle: Lifecycle | _NullLifecycle | None = None
_singleton_lock = threading.Lock()


def get_lifecycle() -> Lifecycle | _NullLifecycle:
    global _lifecycle
    if _lifecycle is None:
        with _singleton_lock:
            if _lifecycle is None:
                _lifecycle = (Lifecycle() if lifecycle_enabled()
                              else NULL_LIFECYCLE)
    return _lifecycle


def charge_state_time(ns: int) -> None:
    """Charge ``ns`` nanoseconds of state pull/push time to the message
    currently executing on THIS thread (ISSUE 16). No-op unless the
    lifecycle plane is on AND an ExecutorContext is set — state ops
    from non-executor threads (benches, servers, tests) charge nobody.
    Accumulates: one run window may perform many state ops."""
    if not get_lifecycle().enabled or ns <= 0:
        return
    try:
        from faabric_tpu.executor.context import ExecutorContext

        if not ExecutorContext.is_set():
            return
        msg = ExecutorContext.get().msg
        msg.lc[PHASE_STATE_ACC] = (
            msg.lc.get(PHASE_STATE_ACC, 0) + int(ns))
    except Exception:  # noqa: BLE001 — attribution must never kill an op
        pass


# ---------------------------------------------------------------------------
# Pure ledger analysis
# ---------------------------------------------------------------------------

def ledger_durations(lc: dict) -> dict[str, float]:
    """Phase durations (seconds) from a stamp ledger: stamps sort by
    TIME (not taxonomy order — a requeue reorders the tail) and each
    gap is attributed to the label of the stamp that ends it. Negative
    gaps (cross-machine clock offset) clamp to 0. Unknown keys keep
    their raw name so a future phase never silently vanishes.

    ``stx`` (ISSUE 16) is a DURATION, not a stamp: accumulated in-run
    state pull/push ns. It is excluded from the stamp walk and carved
    OUT of the run window (``state`` + ``run`` still sum to the old
    ``run``, so the fold's clock-coherence guard is unaffected)."""
    lc = lc or {}
    stamps = sorted(((int(v), k) for k, v in lc.items()
                     if isinstance(v, (int, float))
                     and k != PHASE_STATE_ACC))
    out: dict[str, float] = {}
    for i in range(1, len(stamps)):
        t, key = stamps[i]
        label = PHASE_LABELS.get(key, key)
        out[label] = out.get(label, 0.0) + max(
            0.0, (t - stamps[i - 1][0]) / 1e9)
    acc = lc.get(PHASE_STATE_ACC)
    if isinstance(acc, (int, float)) and acc > 0 and "run" in out:
        state = min(out["run"], int(acc) / 1e9)
        if state > 0:
            out["state"] = state
            out["run"] -= state
    return out


def ledger_span_s(lc: dict) -> float:
    """Last stamp − first stamp, seconds (0 with <2 stamps)."""
    vals = [int(v) for v in (lc or {}).values()
            if isinstance(v, (int, float))]
    if len(vals) < 2:
        return 0.0
    return max(0.0, (max(vals) - min(vals)) / 1e9)


def ledger_e2e_s(lc: dict) -> float | None:
    """Admit → planner-record wall, the e2e figure the digest and the
    SLO tracker consume (None when either endpoint stamp is absent)."""
    lc = lc or {}
    if PHASE_ADMIT not in lc or PHASE_RECORDED not in lc:
        return None
    return max(0.0, (int(lc[PHASE_RECORDED]) - int(lc[PHASE_ADMIT])) / 1e9)


# ---------------------------------------------------------------------------
# Fold store: per-phase streaming estimators + e2e digest
# ---------------------------------------------------------------------------

class _NullLifecycleStats:
    __slots__ = ()
    enabled = False

    def fold(self, msgs) -> None:
        pass

    def snapshot(self) -> dict:
        return {}

    def reset(self) -> None:
        pass


NULL_LIFECYCLE_STATS = _NullLifecycleStats()


class LifecycleStats:
    """Per-phase + end-to-end invocation latency digest. Fed by the
    planner as results are recorded (outside the planner lock); read by
    ``/healthz``, ``GET_TELEMETRY`` and the doctor."""

    # Concurrency contract (tools/concheck.py): estimator maps mutate
    # under one leaf lock; fold/snapshot never hold it across blocking
    # calls. The Prometheus handles are internally locked per series.
    GUARDS = {
        "_phases": "_lock",
        "_e2e": "_lock",
        "_count": "_lock",
        "_failed": "_lock",
    }

    enabled = True

    def __init__(self, half_life: float | None = None) -> None:
        self.half_life = (half_life if half_life is not None else
                          _env_float("FAABRIC_PERF_HALF_LIFE_S", 120.0))
        self._lock = threading.Lock()
        self._phases: dict[str, DecayedStat] = {}
        self._e2e = DecayedStat(self.half_life)
        self._count = 0
        self._failed = 0
        metrics = get_metrics()
        self._h_e2e = metrics.histogram(
            "faabric_lifecycle_e2e_seconds",
            "Admit to planner-recorded invocation latency (phase ledger)")
        self._incoherent = metrics.counter(
            "faabric_lifecycle_incoherent_ledgers_total",
            "Ledgers whose cross-host stamps failed the clock-domain "
            "coherence check (folded as e2e only)")
        self._h_phase: dict[str, object] = {}
        self._metrics = metrics

    def _phase_histogram(self, label: str):
        h = self._h_phase.get(label)
        if h is None:
            h = self._metrics.histogram(
                "faabric_lifecycle_phase_seconds",
                "Per-phase invocation latency from the message ledger",
                phase=label)
            self._h_phase[label] = h
        return h

    def fold(self, msgs) -> None:
        """Fold recorded results' ledgers in. Call OUTSIDE the planner
        lock — a fold is ~10 µs per message across all phases."""
        from faabric_tpu.proto import ReturnValue

        slo = get_slo_tracker()
        for msg in msgs:
            lc = getattr(msg, "lc", None) or {}
            failed = msg.return_value == int(ReturnValue.FAILED)
            e2e = ledger_e2e_s(lc)
            slo.observe(e2e, failed)
            durations = ledger_durations(lc)
            if not durations:
                continue
            # Clock-domain coherence guard: admit and record are BOTH
            # planner-clock stamps, so e2e is always sane — but on a
            # real multi-machine cluster a worker whose monotonic base
            # differs can blow the time-sorted span far past it, and
            # folding that would crown a phantom dominant phase. Such
            # ledgers contribute their (valid) e2e + SLO only.
            if e2e is not None and sum(durations.values()) > \
                    2.0 * e2e + 1.0:
                self._incoherent.inc()
                with self._lock:
                    self._count += 1
                    if failed:
                        self._failed += 1
                    self._e2e.observe(e2e)
                self._h_e2e.observe(e2e)
                continue
            now = time.monotonic()
            with self._lock:
                self._count += 1
                if failed:
                    self._failed += 1
                for label, secs in durations.items():
                    stat = self._phases.get(label)
                    if stat is None:
                        stat = self._phases[label] = DecayedStat(
                            self.half_life)
                    stat.observe(secs, now=now)
                if e2e is not None:
                    self._e2e.observe(e2e, now=now)
            for label, secs in durations.items():
                self._phase_histogram(label).observe(secs)
            if e2e is not None:
                self._h_e2e.observe(e2e)

    @staticmethod
    def _stat_row(stat: DecayedStat) -> dict:
        return {
            "p50_ms": round(stat.quantile(0.50) * 1e3, 4),
            "p90_ms": round(stat.quantile(0.90) * 1e3, 4),
            "p99_ms": round(stat.quantile(0.99) * 1e3, 4),
            "mean_ms": round(stat.mean * 1e3, 4),
            "count": stat.n,
        }

    def snapshot(self) -> dict:
        """JSON-safe digest: per-phase quantiles, the e2e digest, and
        the dominant-phase ranking for the p99 tail — phases ordered by
        their own p99 (in a mostly-serial pipeline the phase with the
        fattest tail is what the e2e p99 is made of)."""
        with self._lock:
            count, failed = self._count, self._failed
            e2e_row = self._stat_row(self._e2e) if self._e2e.n else None
            # Rows read under the lock too: DecayedStat is not
            # thread-safe and fold() mutates these estimators
            rows = {label: self._stat_row(s)
                    for label, s in self._phases.items()}
        e2e_p99 = (e2e_row or {}).get("p99_ms") or 0.0
        dominant = sorted(rows.items(), key=lambda kv: -kv[1]["p99_ms"])
        return {
            "count": count,
            "failed": failed,
            "e2e": e2e_row,
            "phases": rows,
            "dominant_p99": [
                {"phase": label,
                 "p99_ms": row["p99_ms"],
                 "share_of_e2e_p99": (round(row["p99_ms"] / e2e_p99, 4)
                                      if e2e_p99 > 0 else None)}
                for label, row in dominant],
        }

    def reset(self) -> None:
        with self._lock:
            self._phases.clear()
            self._e2e = DecayedStat(self.half_life)
            self._count = 0
            self._failed = 0


# ---------------------------------------------------------------------------
# SLO tracker: declared targets, multi-window burn rates
# ---------------------------------------------------------------------------

def parse_slo_spec(spec: str) -> list[dict]:
    """``FAABRIC_SLO`` grammar: comma-separated ``name=value`` targets.

    - ``pNN_e2e_ms=X``  — the NNth percentile of admit→record e2e must
      stay under X ms; the error budget is the (100−NN)% tail.
    - ``error_rate=F``  — at most fraction F of results may be FAILED.

    Unknown names are skipped with their raw text kept in ``ignored``
    (a typo must not silently disable the whole spec)."""
    targets: list[dict] = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, raw = part.partition("=")
        name = name.strip()
        try:
            value = float(raw)
        except ValueError:
            targets.append({"name": name, "ignored": part})
            continue
        if name.startswith("p") and name.endswith("_e2e_ms"):
            head = name[1:name.index("_")]
            if head.isdigit() and 0 < int(head) < 100:
                targets.append({
                    "name": name, "kind": "latency",
                    "threshold_s": value / 1e3,
                    "budget": (100 - int(head)) / 100.0})
                continue
            targets.append({"name": name, "ignored": part})
        elif name == "error_rate":
            targets.append({"name": name, "kind": "error",
                            "budget": max(1e-9, value)})
        else:
            targets.append({"name": name, "ignored": part})
    return targets


class _NullSloTracker:
    __slots__ = ()
    enabled = False

    def observe(self, e2e_s, failed: bool) -> None:
        pass

    def status(self) -> dict:
        return {}

    def reset(self) -> None:
        pass


NULL_SLO_TRACKER = _NullSloTracker()


class SloTracker:
    """Time-bucketed good/bad counters per declared target, evaluated
    as burn rates over multiple windows (the SRE multi-window pattern):
    ``burn = bad_fraction / budget`` — 1.0 means exactly consuming the
    error budget; ``FAABRIC_SLO_BURN`` (default 2.0) on EVERY window
    (with ≥ ``FAABRIC_SLO_MIN_COUNT`` events in each) trips "burning".
    The rising edge flight-records and dumps — an SLO violation is a
    post-mortem moment."""

    GUARDS = {
        "_buckets": "_lock",
        "_burning": "_lock",
        "_since_eval": "_lock",
    }

    enabled = True

    def __init__(self, spec: str | None = None,
                 windows: list[float] | None = None,
                 bucket_s: float | None = None,
                 burn_threshold: float | None = None,
                 min_count: int | None = None) -> None:
        self.spec = spec if spec is not None else os.environ.get(
            "FAABRIC_SLO", "")
        parsed = parse_slo_spec(self.spec)
        self.targets = [t for t in parsed if "kind" in t]
        self.ignored = [t["ignored"] for t in parsed if "ignored" in t]
        if windows is None:
            raw = os.environ.get("FAABRIC_SLO_WINDOWS", "60,600")
            windows = []
            for tok in raw.split(","):
                try:
                    windows.append(float(tok))
                except ValueError:
                    continue
        self.windows = sorted(set(windows)) or [60.0, 600.0]
        self.bucket_s = (bucket_s if bucket_s is not None else
                         _env_float("FAABRIC_SLO_BUCKET_S", 5.0))
        self.burn_threshold = (burn_threshold if burn_threshold is not None
                               else _env_float("FAABRIC_SLO_BURN", 2.0))
        self.min_count = (min_count if min_count is not None else
                          _env_int("FAABRIC_SLO_MIN_COUNT", 20))
        # Ring: enough buckets to cover the longest window
        self._n_buckets = max(8, int(max(self.windows) / self.bucket_s) + 2)
        self._lock = threading.Lock()
        # Latency targets each get their OWN bad counter slot: two
        # declared percentiles (p50 + p99) must not share one — a p50
        # miss is not a p99 miss, and a shared counter would false-burn
        # the stricter-budget target off the looser threshold
        self._latency_targets = [t for t in self.targets
                                 if t["kind"] == "latency"]
        # bucket idx → [epoch_bucket, total, err_bad, [lat_bad/target]]
        self._buckets: list = [None] * self._n_buckets
        self._burning: dict[str, bool] = {}
        self._since_eval = 0
        self._gauges: dict[tuple, object] = {}
        self._burns_total = get_metrics().counter(
            "faabric_slo_burns_total",
            "SLO targets newly entering the burning state")

    # ------------------------------------------------------------------
    def observe(self, e2e_s: float | None, failed: bool) -> None:
        if not self.targets:
            return
        epoch = int(time.monotonic() / self.bucket_s)
        run_eval = False
        with self._lock:
            i = epoch % self._n_buckets
            b = self._buckets[i]
            if b is None or b[0] != epoch:
                b = self._buckets[i] = [
                    epoch, 0, 0, [0] * len(self._latency_targets)]
            b[1] += 1
            if failed:
                b[2] += 1
            if e2e_s is not None:
                for j, t in enumerate(self._latency_targets):
                    if e2e_s > t["threshold_s"]:
                        b[3][j] += 1
            self._since_eval += 1
            if self._since_eval >= 64:
                self._since_eval = 0
                run_eval = True
        if run_eval:
            self.status()

    def _window_counts_locked(self, window_s: float, now_epoch: int
                              ) -> tuple[int, int, list[int]]:
        # At least the current bucket: a window narrower than the
        # bucket width must still see events, not silently read empty
        lo = now_epoch - max(1, round(window_s / self.bucket_s))
        total = err_bad = 0
        lat_bad = [0] * len(self._latency_targets)
        for b in self._buckets:
            if b is not None and lo < b[0] <= now_epoch:
                total += b[1]
                err_bad += b[2]
                for j, n in enumerate(b[3]):
                    lat_bad[j] += n
        return total, err_bad, lat_bad

    def status(self) -> dict:
        """Current burn rates per target/window; evaluates the burning
        edge (flight record + counter on a rising edge)."""
        if not self.targets:
            return {"spec": self.spec, "targets": []}
        now_epoch = int(time.monotonic() / self.bucket_s)
        newly_burning: list[tuple[str, dict]] = []
        out_targets = []
        with self._lock:
            per_window = {w: self._window_counts_locked(w, now_epoch)
                          for w in self.windows}
            for t in self.targets:
                lat_idx = (self._latency_targets.index(t)
                           if t["kind"] == "latency" else -1)
                rows = {}
                burning = True
                for w, (total, err_bad, lat_bad) in per_window.items():
                    bad = (lat_bad[lat_idx] if t["kind"] == "latency"
                           else err_bad)
                    frac = bad / total if total else 0.0
                    burn = frac / t["budget"]
                    rows[f"{int(w)}s"] = {
                        "total": total, "bad": bad,
                        "burn": round(burn, 3)}
                    if total < self.min_count or burn < self.burn_threshold:
                        burning = False
                was = self._burning.get(t["name"], False)
                self._burning[t["name"]] = burning
                if burning and not was:
                    newly_burning.append((t["name"], dict(rows)))
                out_targets.append({
                    "name": t["name"], "kind": t["kind"],
                    "budget": t["budget"],
                    "threshold_ms": (round(t["threshold_s"] * 1e3, 3)
                                     if "threshold_s" in t else None),
                    "windows": rows, "burning": burning})
        for row in out_targets:
            for wname, wrow in row["windows"].items():
                key = (row["name"], wname)
                g = self._gauges.get(key)
                if g is None:
                    g = self._gauges[key] = get_metrics().gauge(
                        "faabric_slo_burn_rate",
                        "Current SLO burn rate (bad fraction / budget)",
                        slo=row["name"], window=wname)
                g.set(wrow["burn"])
        if newly_burning:
            from faabric_tpu.telemetry.flight import (
                flight_dump,
                flight_record,
            )

            for name, rows in newly_burning:
                self._burns_total.inc()
                flight_record("slo_burn", slo=name, windows=rows)
            flight_dump("slo_burn")
        return {"spec": self.spec, "burnThreshold": self.burn_threshold,
                "windowsSeconds": [int(w) for w in self.windows],
                "ignored": self.ignored, "targets": out_targets}

    def reset(self) -> None:
        with self._lock:
            self._buckets = [None] * self._n_buckets
            self._burning.clear()


# ---------------------------------------------------------------------------
# Singletons
# ---------------------------------------------------------------------------

_stats: LifecycleStats | None = None
_slo: SloTracker | None = None


def get_lifecycle_stats() -> LifecycleStats | _NullLifecycleStats:
    if not lifecycle_enabled():
        return NULL_LIFECYCLE_STATS
    global _stats
    if _stats is None:
        with _singleton_lock:
            if _stats is None:
                _stats = LifecycleStats()
    return _stats


def get_slo_tracker() -> SloTracker | _NullSloTracker:
    if not lifecycle_enabled():
        return NULL_SLO_TRACKER
    global _slo
    if _slo is None:
        with _singleton_lock:
            if _slo is None:
                _slo = SloTracker()
    return _slo


def reset_lifecycle() -> None:
    """Test hook: drop every singleton so the next use re-reads env."""
    global _lifecycle, _stats, _slo
    with _singleton_lock:
        _lifecycle = None
        _stats = None
        _slo = None
