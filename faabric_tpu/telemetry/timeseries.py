"""Continuous time-series ring (ISSUE 14): the doctor needs *trends*.

``/healthz`` answers "what is the ingress depth NOW"; nobody could
answer "has it been growing for the last minute" — the difference
between a burst the tick will drain and a capacity exhaustion in
progress. This module keeps a preallocated ring of sampled gauges per
process:

- every process samples its own resource gauges (RSS, CPU%, threads,
  fds, GC — ``telemetry/procstats.py``);
- the planner registers control-plane series on top (ingress depth,
  shed total, free-slot watermark, tick duration, result backlog,
  in-flight messages);
- workers add their executor count.

A :class:`TimeSeriesSampler` (``PeriodicBackgroundThread``) drives
``sample()`` every ``FAABRIC_TIMESERIES_INTERVAL_S`` seconds (default
1.0); the ring holds ``FAABRIC_TIMESERIES_RING`` points per series
(default 512 ≈ 8.5 minutes at 1 Hz). Snapshots ride ``GET_TELEMETRY``
(``timeseries`` block) and the planner merges every host's ring behind
``GET /timeseries``; worker HTTP endpoints serve their local ring on
the same path. Timestamps are wall-clock so hosts' series line up.

``FAABRIC_METRICS=0`` (or ``FAABRIC_TIMESERIES=0``) returns the shared
no-op ring: registrations and samples cost one no-op call.
"""

from __future__ import annotations

import math
import os
import threading
import time

from faabric_tpu.telemetry.metrics import metrics_enabled
from faabric_tpu.util.config import _env_float, _env_int
from faabric_tpu.util.logging import get_logger
from faabric_tpu.util.periodic import PeriodicBackgroundThread

logger = get_logger(__name__)

DEFAULT_RING = 512
DEFAULT_INTERVAL_S = 1.0


def timeseries_enabled() -> bool:
    return (metrics_enabled()
            and os.environ.get("FAABRIC_TIMESERIES", "1")
            not in ("0", "false", "off"))


class _NullTimeSeries:
    __slots__ = ()
    enabled = False

    def register(self, name: str, fn) -> None:
        pass

    def unregister(self, name: str, fn=None) -> None:
        pass

    def sample(self) -> None:
        pass

    def snapshot(self, last: int | None = None) -> dict:
        return {}

    def reset(self) -> None:
        pass


NULL_TIMESERIES = _NullTimeSeries()


class _Series:
    """One preallocated ring of (implicit-timestamp) float samples. The
    shared timestamp ring lives on the owner — every series samples on
    the same tick, so storing the clock once per tick keeps a 16-series
    ring at 16 floats per sample, not 32."""

    __slots__ = ("values", "fn")

    def __init__(self, capacity: int, fn) -> None:
        self.values = [math.nan] * capacity
        self.fn = fn


class TimeSeriesRing:
    """Named gauge samplers + their preallocated history rings."""

    # Concurrency contract (tools/concheck.py): registration map, ring
    # cursor and the timestamp ring mutate under one leaf lock; sampler
    # callables run OUTSIDE it (a stuck gauge must not wedge snapshot
    # readers), writing each value with one locked slot store.
    GUARDS = {
        "_series": "_lock",
        "_ts": "_lock",
        "_cursor": "_lock",
    }

    enabled = True

    def __init__(self, capacity: int | None = None) -> None:
        self.capacity = max(8, capacity if capacity is not None else
                            _env_int("FAABRIC_TIMESERIES_RING",
                                     DEFAULT_RING))
        self._lock = threading.Lock()
        self._series: dict[str, _Series] = {}
        self._ts = [0.0] * self.capacity
        self._cursor = 0  # total samples taken (monotonic)

    # ------------------------------------------------------------------
    def register(self, name: str, fn) -> None:
        """Register (or replace) a gauge sampler: ``fn() -> float``.
        Replacement is deliberate — in-process multi-runtime tests
        re-register per-host series and the latest runtime wins."""
        with self._lock:
            s = self._series.get(name)
            if s is None:
                self._series[name] = _Series(self.capacity, fn)
            else:
                s.fn = fn

    def unregister(self, name: str, fn=None) -> None:
        """Remove a series. With ``fn`` given, remove ONLY if the live
        sampler is still that callable — a stopping owner must not kill
        the series a co-resident runtime re-registered over it."""
        with self._lock:
            s = self._series.get(name)
            if s is not None and (fn is None or s.fn is fn):
                del self._series[name]

    # ------------------------------------------------------------------
    def sample(self) -> None:
        """Take one sample of every registered series. Gauge callables
        run lock-free; a raising gauge records NaN for this tick and is
        kept (a transiently dead accessor must not lose its series)."""
        with self._lock:
            fns = [(name, s.fn) for name, s in self._series.items()]
        values: dict[str, float] = {}
        for name, fn in fns:
            try:
                values[name] = float(fn())
            except Exception:  # noqa: BLE001 — one bad gauge ≠ no ring
                values[name] = math.nan
        now = time.time()
        with self._lock:
            slot = self._cursor % self.capacity
            self._ts[slot] = now
            for name, v in values.items():
                s = self._series.get(name)
                if s is not None:
                    s.values[slot] = v
            self._cursor += 1

    # ------------------------------------------------------------------
    def snapshot(self, last: int | None = None) -> dict:
        """JSON-safe dump, oldest → newest: ``{"interval_hint_s", ...,
        "series": {name: [[wall_ts, value], ...]}}``. NaN samples (gauge
        failed, or the series registered mid-ring) are dropped per
        point."""
        with self._lock:
            cursor = self._cursor
            ts = list(self._ts)
            series = {name: list(s.values)
                      for name, s in self._series.items()}
        n = min(cursor, self.capacity)
        if last is not None:
            n = min(n, max(0, last))
        # Chronological slot order ending at the newest sample
        slots = [(cursor - n + i) % self.capacity for i in range(n)]
        out_series: dict[str, list] = {}
        for name, vals in series.items():
            pts = []
            for slot in slots:
                v = vals[slot]
                if not math.isnan(v):
                    pts.append([round(ts[slot], 3), v])
            out_series[name] = pts
        return {
            "capacity": self.capacity,
            "samples_taken": cursor,
            "interval_hint_s": _env_float("FAABRIC_TIMESERIES_INTERVAL_S",
                                          DEFAULT_INTERVAL_S),
            "series": out_series,
        }

    def reset(self) -> None:
        with self._lock:
            self._series.clear()
            self._ts = [0.0] * self.capacity
            self._cursor = 0


class TimeSeriesSampler(PeriodicBackgroundThread):
    thread_name = "telemetry/sampler"

    def __init__(self, ring: TimeSeriesRing) -> None:
        super().__init__()
        self.ring = ring

    def do_work(self) -> None:
        self.ring.sample()


# ---------------------------------------------------------------------------
# Singletons + the shared sampler (refcounted: a planner server and
# worker runtimes can coexist in one test process; the sampler stops
# only when the LAST user stops)
# ---------------------------------------------------------------------------

_ring: TimeSeriesRing | None = None
_sampler: TimeSeriesSampler | None = None
_sampler_users = 0
_singleton_lock = threading.Lock()


def get_timeseries() -> TimeSeriesRing | _NullTimeSeries:
    if not timeseries_enabled():
        return NULL_TIMESERIES
    global _ring
    if _ring is None:
        with _singleton_lock:
            if _ring is None:
                ring = TimeSeriesRing()
                _register_process_series(ring)
                _ring = ring
    return _ring


def _register_process_series(ring: TimeSeriesRing) -> None:
    """Every host samples its own process resources (ISSUE 14
    satellite): the collector feeds both the Prometheus gauges and
    this ring."""
    from faabric_tpu.telemetry.procstats import get_proc_stats

    stats = get_proc_stats()
    if not stats.enabled:
        return

    def series(key: str):
        return lambda: stats.refresh().get(key, math.nan)

    # One refresh() per tick would be ideal; refresh() throttles itself
    # (min interval), so per-series calls within one sample() tick cost
    # one /proc read for the first and cached dict hits for the rest.
    for key, name in (("rss_bytes", "proc_rss_bytes"),
                      ("cpu_percent", "proc_cpu_percent"),
                      ("threads", "proc_threads"),
                      ("open_fds", "proc_open_fds"),
                      ("gc_collections", "proc_gc_collections")):
        ring.register(name, series(key))


def start_sampler() -> None:
    """Start (or share) the per-process sampler thread. Pair every call
    with ``stop_sampler()`` — server/runtime start/stop cycles must not
    leak the thread (the dist leak gate enforces it)."""
    if not timeseries_enabled():
        return
    ring = get_timeseries()
    global _sampler, _sampler_users
    with _singleton_lock:
        # The whole refcount+thread transition happens under the lock:
        # a stop (1→0) releasing before its join racing a start (0→1)
        # would otherwise kill the thread the new owner believes it
        # just started. start() is one cheap thread spawn; stop()'s
        # join is bounded (5 s).
        _sampler_users += 1
        if _sampler is None:
            _sampler = TimeSeriesSampler(ring)
        _sampler.start(max(0.01,
                           _env_float("FAABRIC_TIMESERIES_INTERVAL_S",
                                      DEFAULT_INTERVAL_S)))


def stop_sampler() -> None:
    global _sampler_users
    with _singleton_lock:
        _sampler_users = max(0, _sampler_users - 1)
        if _sampler_users > 0 or _sampler is None:
            return
        _sampler.stop()  # concheck: ok(blocking-under-lock) — bounded
        # 5 s join, and the lock IS the start/stop serialization (see
        # start_sampler)


def reset_timeseries() -> None:
    """Test hook: stop any sampler and drop the ring singleton."""
    global _ring, _sampler, _sampler_users
    with _singleton_lock:
        sampler = _sampler
        _sampler = None
        _sampler_users = 0
        _ring = None
    if sampler is not None:
        sampler.stop()
