"""Runtime telemetry: the metrics registry + span tracer.

Two layers, one import surface:

- :mod:`faabric_tpu.telemetry.metrics` — process-wide counters, gauges
  and fixed-bucket histograms with Prometheus text export (served by the
  planner endpoint's ``GET /metrics``, aggregated from every host).
- :mod:`faabric_tpu.telemetry.tracer` — nestable spans with Chrome
  ``trace_event`` export (``GET /trace``) and the text summary that
  supersedes ``util.clock.prof_summary``.

See docs/telemetry.md for env vars and capture recipes.
"""

from faabric_tpu.telemetry.commmatrix import (
    NULL_COMM_MATRIX,
    CommMatrix,
    families_from_cells,
    get_comm_matrix,
    merge_cell_rows,
)
from faabric_tpu.telemetry.flight import (
    NULL_FLIGHT,
    FlightRecorder,
    flight_dump,
    flight_record,
    get_flight,
)
from faabric_tpu.telemetry.perfprofile import (
    NULL_COLLECTIVE_PROFILER,
    NULL_PERF_STORE,
    CollectiveProfiler,
    PerfProfileStore,
    aggregate_perf,
    critical_path,
    find_stragglers,
    get_collective_profiler,
    get_perf_store,
    merge_collective_series,
    merge_link_rows,
    perf_telemetry_block,
    persist_cluster,
    reset_perf_profile,
)
from faabric_tpu.telemetry.metrics import (
    DEFAULT_BUCKETS,
    NULL_METRIC,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_metrics,
    metrics_enabled,
    render_snapshots,
    set_metrics_enabled,
    snapshot_delta,
)
from faabric_tpu.telemetry.tracer import (
    NULL_SPAN,
    Tracer,
    chrome_trace,
    chrome_trace_json,
    current_trace_context,
    decode_trace_context,
    encode_trace_context,
    flow_end,
    flow_id_for,
    flow_start,
    get_tracer,
    instant,
    reset_tracing,
    set_process_label,
    set_tracing,
    span,
    span_from_remote,
    summary_data,
    text_summary,
    trace_events,
    tracing_enabled,
)

__all__ = [
    "DEFAULT_BUCKETS",
    "NULL_COLLECTIVE_PROFILER",
    "NULL_COMM_MATRIX",
    "NULL_FLIGHT",
    "NULL_METRIC",
    "NULL_PERF_STORE",
    "NULL_SPAN",
    "CollectiveProfiler",
    "CommMatrix",
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "PerfProfileStore",
    "Tracer",
    "aggregate_perf",
    "critical_path",
    "find_stragglers",
    "get_collective_profiler",
    "get_perf_store",
    "merge_collective_series",
    "merge_link_rows",
    "perf_telemetry_block",
    "persist_cluster",
    "reset_perf_profile",
    "chrome_trace",
    "chrome_trace_json",
    "current_trace_context",
    "decode_trace_context",
    "encode_trace_context",
    "families_from_cells",
    "flight_dump",
    "flight_record",
    "flow_end",
    "flow_id_for",
    "flow_start",
    "get_comm_matrix",
    "get_flight",
    "get_metrics",
    "get_tracer",
    "instant",
    "merge_cell_rows",
    "metrics_enabled",
    "render_snapshots",
    "reset_tracing",
    "set_metrics_enabled",
    "set_process_label",
    "set_tracing",
    "snapshot_delta",
    "span",
    "span_from_remote",
    "summary_data",
    "text_summary",
    "trace_events",
    "tracing_enabled",
]
