"""Span tracer: nestable, thread-/async-safe timing spans with Chrome
``trace_event`` export.

A span carries (subsystem, label, attrs); nesting is tracked through a
``contextvars.ContextVar``, so spans opened on different threads (each
thread starts with an empty context) or interleaved asyncio tasks never
corrupt each other's stacks. Completed spans land in a bounded ring
buffer as Chrome "X" (complete) events — load the ``chrome_trace()``
dump in ``chrome://tracing`` or Perfetto and the per-thread nesting
renders as flame graphs. Aggregate totals are kept separately (complete
even after the ring buffer wraps) and feed ``text_summary()``, the
successor of ``util.clock.prof_summary``.

Enablement: ``FAABRIC_TRACING=1`` (or the legacy
``FAABRIC_SELF_TRACING=1``) at process start, or ``set_tracing(True)``
programmatically (tests, targeted capture). Disabled mode is a
zero-allocation fast path: ``span(...)`` returns one shared no-op
context manager.

Timestamps: wall-clock-anchored microseconds (``wall_epoch +
monotonic_delta``), so traces captured by co-located processes (the
multi-process bulk plane) line up on one Perfetto timeline.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import random
import threading
import time
from collections import deque

_current: contextvars.ContextVar = contextvars.ContextVar(
    "faabric_current_span", default=None)

# ---------------------------------------------------------------------------
# Trace context: span identity + cross-host propagation
# ---------------------------------------------------------------------------
# Span ids are process-unique JSON-safe ints: a random per-process tag in
# the high bits (kills cross-host collisions without coordination) and a
# monotonic counter below. Kept under 2^53 so they survive any JSON
# round-trip (JS number precision).
_PROC_TAG = (random.getrandbits(21) ^ (os.getpid() & 0xFFFFF)) & 0x1FFFFF
_span_ids = itertools.count(1)


def _new_span_id() -> int:
    return (_PROC_TAG << 32) | (next(_span_ids) & 0xFFFFFFFF)


def encode_trace_context(trace_id: int, span_id: int) -> str:
    """Compact wire form carried in message headers (``_tc`` key):
    ``<trace_id hex>.<span_id hex>``."""
    return f"{trace_id:x}.{span_id:x}"


def decode_trace_context(text) -> tuple[int, int] | None:
    """Inverse of :func:`encode_trace_context`; None on anything
    malformed (a corrupt header must degrade to an unlinked span, not an
    exception on the server's handler path)."""
    if not isinstance(text, str) or "." not in text:
        return None
    head, _, tail = text.partition(".")
    try:
        trace_id, span_id = int(head, 16), int(tail, 16)
    except ValueError:
        return None
    if trace_id <= 0 or span_id <= 0:
        return None
    return trace_id, span_id


def current_trace_context() -> str | None:
    """The active span's (trace id, span id) in wire form, or None when
    no span is open (or tracing is off). Attach this to outbound message
    headers; the receiving side opens its handler span with
    ``remote=...`` so the merged trace shows the causal parent→child
    link across hosts."""
    span = _current.get()
    if span is None:
        return None
    return encode_trace_context(span.trace_id, span.span_id)


def flow_id_for(group_id: int, send_idx: int, recv_idx: int,
                channel: int, seq: int) -> int:
    """Deterministic flow-event id both ends of a PTP message can derive
    independently (the bulk plane's fixed frame header has no room for a
    trace context; the sequence tuple IS the message identity). Plain
    multiply-xor mix — Python's hash() is salted per process and would
    never match across hosts."""
    h = (group_id & 0xFFFFFFFFFFFF) * 0x9E3779B1
    h ^= (send_idx + 1) * 0x85EBCA77
    h ^= (recv_idx + 1) * 0xC2B2AE3D
    h ^= (channel + 1) * 0x27D4EB2F
    h ^= (seq + 2) * 0x165667B1
    return h & ((1 << 53) - 1)


class _NullSpan:
    """Shared no-op span returned while tracing is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "subsystem", "label", "attrs", "_t0", "_token",
                 "span_id", "trace_id", "parent_span_id", "_remote")

    def __init__(self, tracer: "Tracer", subsystem: str, label: str,
                 attrs: dict, remote: tuple[int, int] | None = None) -> None:
        self._tracer = tracer
        self.subsystem = subsystem
        self.label = label
        self.attrs = attrs
        self._remote = remote

    def __enter__(self):
        self.span_id = _new_span_id()
        parent = _current.get()
        if parent is not None:
            self.attrs.setdefault(
                "parent", f"{parent.subsystem}/{parent.label}")
            self.trace_id = parent.trace_id
            self.parent_span_id = parent.span_id
        elif self._remote is not None:
            # Cross-host continuation: the sender's (trace, span) ids
            # arrived in the message header — the merged /trace links
            # this handler span to its remote parent instead of showing
            # a per-host island
            self.trace_id, self.parent_span_id = self._remote
            self.attrs["remote_parent"] = True
        else:
            self.trace_id = self.span_id  # root mints the trace id
            self.parent_span_id = 0
        self._token = _current.set(self)
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        end = time.monotonic()
        _current.reset(self._token)
        self._tracer._record(self, self._t0, end)
        return False


class Tracer:
    def __init__(self, enabled: bool, maxlen: int) -> None:
        self._enabled = enabled
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=maxlen)
        self._totals: dict[str, float] = {}
        self._counts: dict[str, int] = {}
        self._tid_names: dict[int, str] = {}
        self._pid = os.getpid()
        self.process_label = f"faabric-{self._pid}"
        # Wall anchor for cross-process alignment of monotonic stamps
        self._wall0 = time.time() - time.monotonic()

    # -- span creation --------------------------------------------------
    def span(self, subsystem: str, label: str, **attrs):
        if not self._enabled:
            return NULL_SPAN
        return _Span(self, subsystem, label, attrs)

    def span_from_remote(self, subsystem: str, label: str,
                         context, **attrs):
        """A span whose parent is a REMOTE span: ``context`` is the wire
        form produced by :func:`current_trace_context` on the sending
        host (or None/garbage → plain span). A locally-nested span keeps
        its local parent; the remote link only applies at the root of
        this host's handling."""
        if not self._enabled:
            return NULL_SPAN
        return _Span(self, subsystem, label, attrs,
                     remote=decode_trace_context(context))

    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, on: bool) -> None:
        self._enabled = on

    # -- recording ------------------------------------------------------
    def _record(self, span: _Span, t0: float, t1: float) -> None:
        tid = threading.get_ident()
        attrs = span.attrs
        attrs["span_id"] = span.span_id
        attrs["trace_id"] = span.trace_id
        if span.parent_span_id:
            attrs["parent_span_id"] = span.parent_span_id
        event = {
            "name": span.label,
            "cat": span.subsystem,
            "ph": "X",
            "ts": (self._wall0 + t0) * 1e6,
            "dur": (t1 - t0) * 1e6,
            "pid": self._pid,
            "tid": tid,
            "args": attrs,
        }
        key = f"{span.subsystem}/{span.label}"
        with self._lock:
            self._events.append(event)
            self._totals[key] = self._totals.get(key, 0.0) + (t1 - t0)
            self._counts[key] = self._counts.get(key, 0) + 1
            # Last-write-wins: CPython recycles thread idents, so the
            # row label should follow the ident's CURRENT owner
            self._tid_names[tid] = threading.current_thread().name

    def _emit(self, event: dict) -> None:
        tid = threading.get_ident()
        event["pid"] = self._pid
        event["tid"] = tid
        with self._lock:
            self._events.append(event)
            self._tid_names[tid] = threading.current_thread().name

    def instant(self, subsystem: str, label: str, **attrs) -> None:
        """A zero-duration marker event (Chrome 'i' phase) — fault
        firings, state transitions."""
        if not self._enabled:
            return
        event = {"name": label, "cat": subsystem, "ph": "i", "s": "t",
                 "ts": (self._wall0 + time.monotonic()) * 1e6}
        if attrs:
            event["args"] = attrs
        self._emit(event)

    def flow_start(self, flow: int, name: str = "msg", **attrs) -> None:
        """Flow-arrow origin: emitted INSIDE a send span so Perfetto
        binds the arrow tail to it. The matching flow_end on the
        receiving host (same deterministic id) draws the cross-process
        send→recv edge."""
        if not self._enabled:
            return
        event = {"name": name, "cat": "flow", "ph": "s", "id": flow,
                 "ts": (self._wall0 + time.monotonic()) * 1e6}
        if attrs:
            event["args"] = attrs
        self._emit(event)

    def flow_end(self, flow: int, name: str = "msg", **attrs) -> None:
        if not self._enabled:
            return
        event = {"name": name, "cat": "flow", "ph": "f", "bp": "e",
                 "id": flow,
                 "ts": (self._wall0 + time.monotonic()) * 1e6}
        if attrs:
            event["args"] = attrs
        self._emit(event)

    # -- export ---------------------------------------------------------
    def trace_events(self) -> list[dict]:
        """Completed spans plus process/thread-name metadata records."""
        with self._lock:
            events = list(self._events)
            tid_names = dict(self._tid_names)
        meta: list[dict] = [{
            "name": "process_name", "ph": "M", "pid": self._pid, "tid": 0,
            "args": {"name": self.process_label},
        }]
        for tid, name in tid_names.items():
            meta.append({"name": "thread_name", "ph": "M", "pid": self._pid,
                         "tid": tid, "args": {"name": name}})
        return meta + events

    def chrome_trace(self) -> dict:
        return {"traceEvents": self.trace_events(),
                "displayTimeUnit": "ms"}

    def chrome_trace_json(self) -> str:
        return json.dumps(self.chrome_trace())

    def summary_data(self) -> dict[str, dict]:
        with self._lock:
            return {k: {"total_s": self._totals[k],
                        "count": self._counts[k]}
                    for k in self._totals}

    def text_summary(self) -> str:
        with self._lock:
            lines = ["--- PROF summary ---"]
            for key in sorted(self._totals):
                lines.append(
                    f"{key:<40} total={self._totals[key] * 1000:.2f}ms "
                    f"n={self._counts[key]}")
            return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._totals.clear()
            self._counts.clear()
            self._tid_names.clear()


def _env_enabled() -> bool:
    return (os.environ.get("FAABRIC_TRACING", "0") == "1"
            or os.environ.get("FAABRIC_SELF_TRACING", "0") == "1")


_tracer: Tracer | None = None
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    global _tracer
    if _tracer is None:
        with _tracer_lock:
            if _tracer is None:
                maxlen = int(os.environ.get("FAABRIC_TRACE_BUFFER", 65536))
                _tracer = Tracer(_env_enabled(), maxlen)
    return _tracer


# -- module-level conveniences (the API instrumentation sites use) ------
def span(subsystem: str, label: str, **attrs):
    return get_tracer().span(subsystem, label, **attrs)


def span_from_remote(subsystem: str, label: str, context, **attrs):
    return get_tracer().span_from_remote(subsystem, label, context, **attrs)


def instant(subsystem: str, label: str, **attrs) -> None:
    get_tracer().instant(subsystem, label, **attrs)


def flow_start(flow: int, name: str = "msg", **attrs) -> None:
    get_tracer().flow_start(flow, name, **attrs)


def flow_end(flow: int, name: str = "msg", **attrs) -> None:
    get_tracer().flow_end(flow, name, **attrs)


def tracing_enabled() -> bool:
    return get_tracer().enabled()


def set_tracing(on: bool) -> None:
    get_tracer().set_enabled(on)


def set_process_label(label: str) -> None:
    get_tracer().process_label = label


def trace_events() -> list[dict]:
    return get_tracer().trace_events()


def chrome_trace() -> dict:
    return get_tracer().chrome_trace()


def chrome_trace_json() -> str:
    return get_tracer().chrome_trace_json()


def text_summary() -> str:
    return get_tracer().text_summary()


def summary_data() -> dict[str, dict]:
    return get_tracer().summary_data()


def reset_tracing() -> None:
    get_tracer().reset()
