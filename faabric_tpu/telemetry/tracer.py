"""Span tracer: nestable, thread-/async-safe timing spans with Chrome
``trace_event`` export.

A span carries (subsystem, label, attrs); nesting is tracked through a
``contextvars.ContextVar``, so spans opened on different threads (each
thread starts with an empty context) or interleaved asyncio tasks never
corrupt each other's stacks. Completed spans land in a bounded ring
buffer as Chrome "X" (complete) events — load the ``chrome_trace()``
dump in ``chrome://tracing`` or Perfetto and the per-thread nesting
renders as flame graphs. Aggregate totals are kept separately (complete
even after the ring buffer wraps) and feed ``text_summary()``, the
successor of ``util.clock.prof_summary``.

Enablement: ``FAABRIC_TRACING=1`` (or the legacy
``FAABRIC_SELF_TRACING=1``) at process start, or ``set_tracing(True)``
programmatically (tests, targeted capture). Disabled mode is a
zero-allocation fast path: ``span(...)`` returns one shared no-op
context manager.

Timestamps: wall-clock-anchored microseconds (``wall_epoch +
monotonic_delta``), so traces captured by co-located processes (the
multi-process bulk plane) line up on one Perfetto timeline.
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
from collections import deque

_current: contextvars.ContextVar = contextvars.ContextVar(
    "faabric_current_span", default=None)


class _NullSpan:
    """Shared no-op span returned while tracing is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "subsystem", "label", "attrs", "_t0", "_token")

    def __init__(self, tracer: "Tracer", subsystem: str, label: str,
                 attrs: dict) -> None:
        self._tracer = tracer
        self.subsystem = subsystem
        self.label = label
        self.attrs = attrs

    def __enter__(self):
        parent = _current.get()
        if parent is not None:
            self.attrs.setdefault(
                "parent", f"{parent.subsystem}/{parent.label}")
        self._token = _current.set(self)
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        end = time.monotonic()
        _current.reset(self._token)
        self._tracer._record(self, self._t0, end)
        return False


class Tracer:
    def __init__(self, enabled: bool, maxlen: int) -> None:
        self._enabled = enabled
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=maxlen)
        self._totals: dict[str, float] = {}
        self._counts: dict[str, int] = {}
        self._tid_names: dict[int, str] = {}
        self._pid = os.getpid()
        self.process_label = f"faabric-{self._pid}"
        # Wall anchor for cross-process alignment of monotonic stamps
        self._wall0 = time.time() - time.monotonic()

    # -- span creation --------------------------------------------------
    def span(self, subsystem: str, label: str, **attrs):
        if not self._enabled:
            return NULL_SPAN
        return _Span(self, subsystem, label, attrs)

    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, on: bool) -> None:
        self._enabled = on

    # -- recording ------------------------------------------------------
    def _record(self, span: _Span, t0: float, t1: float) -> None:
        tid = threading.get_ident()
        event = {
            "name": span.label,
            "cat": span.subsystem,
            "ph": "X",
            "ts": (self._wall0 + t0) * 1e6,
            "dur": (t1 - t0) * 1e6,
            "pid": self._pid,
            "tid": tid,
        }
        if span.attrs:
            event["args"] = span.attrs
        key = f"{span.subsystem}/{span.label}"
        with self._lock:
            self._events.append(event)
            self._totals[key] = self._totals.get(key, 0.0) + (t1 - t0)
            self._counts[key] = self._counts.get(key, 0) + 1
            # Last-write-wins: CPython recycles thread idents, so the
            # row label should follow the ident's CURRENT owner
            self._tid_names[tid] = threading.current_thread().name

    # -- export ---------------------------------------------------------
    def trace_events(self) -> list[dict]:
        """Completed spans plus process/thread-name metadata records."""
        with self._lock:
            events = list(self._events)
            tid_names = dict(self._tid_names)
        meta: list[dict] = [{
            "name": "process_name", "ph": "M", "pid": self._pid, "tid": 0,
            "args": {"name": self.process_label},
        }]
        for tid, name in tid_names.items():
            meta.append({"name": "thread_name", "ph": "M", "pid": self._pid,
                         "tid": tid, "args": {"name": name}})
        return meta + events

    def chrome_trace(self) -> dict:
        return {"traceEvents": self.trace_events(),
                "displayTimeUnit": "ms"}

    def chrome_trace_json(self) -> str:
        return json.dumps(self.chrome_trace())

    def summary_data(self) -> dict[str, dict]:
        with self._lock:
            return {k: {"total_s": self._totals[k],
                        "count": self._counts[k]}
                    for k in self._totals}

    def text_summary(self) -> str:
        with self._lock:
            lines = ["--- PROF summary ---"]
            for key in sorted(self._totals):
                lines.append(
                    f"{key:<40} total={self._totals[key] * 1000:.2f}ms "
                    f"n={self._counts[key]}")
            return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._totals.clear()
            self._counts.clear()
            self._tid_names.clear()


def _env_enabled() -> bool:
    return (os.environ.get("FAABRIC_TRACING", "0") == "1"
            or os.environ.get("FAABRIC_SELF_TRACING", "0") == "1")


_tracer: Tracer | None = None
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    global _tracer
    if _tracer is None:
        with _tracer_lock:
            if _tracer is None:
                maxlen = int(os.environ.get("FAABRIC_TRACE_BUFFER", 65536))
                _tracer = Tracer(_env_enabled(), maxlen)
    return _tracer


# -- module-level conveniences (the API instrumentation sites use) ------
def span(subsystem: str, label: str, **attrs):
    return get_tracer().span(subsystem, label, **attrs)


def tracing_enabled() -> bool:
    return get_tracer().enabled()


def set_tracing(on: bool) -> None:
    get_tracer().set_enabled(on)


def set_process_label(label: str) -> None:
    get_tracer().process_label = label


def trace_events() -> list[dict]:
    return get_tracer().trace_events()


def chrome_trace() -> dict:
    return get_tracer().chrome_trace()


def chrome_trace_json() -> str:
    return get_tracer().chrome_trace_json()


def text_summary() -> str:
    return get_tracer().text_summary()


def summary_data() -> dict[str, dict]:
    return get_tracer().summary_data()


def reset_tracing() -> None:
    get_tracer().reset()
