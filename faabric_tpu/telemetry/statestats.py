"""Per-key state & snapshot access ledger + cluster state map (ISSUE 16).

ROADMAP item 2 rebuilds ``state/`` + ``snapshot/`` into a production
sharded KV, but those packages were the last fully dark subsystems —
the rebuild would start from folklore about hot keys, pull
amplification and master skew. This module is the measurement layer it
starts from instead, the same estimator shapes as the perf profile
(PR 12):

- :class:`StateStatsStore` — per-key ledger of every state op this
  process performed (get/set/get_chunk/set_chunk/pull/push_full/
  push_partial/append/lock_global): op counts, bytes, chunk counts,
  dirty-chunk ratio and latency (:class:`DecayedStat` log-bucket
  quantiles), pull amplification (total vs first-time chunk pulls),
  global-lock wait/stall accounting, plus store-level snapshot
  lifecycle estimators (dirty pages, diff encode/apply sizes and ms,
  restore latency). Cardinality-capped like the comm matrix: keys past
  ``FAABRIC_STATE_MAX_KEYS`` collapse into ``other``.
- Prometheus families ``faabric_state_*`` / ``faabric_snapshot_*``
  (per-op totals — per-KEY detail rides the telemetry block, not label
  cardinality) and ``/timeseries`` gauges: ``state_resident_bytes``,
  ``state_dirty_chunks``, ``snapshot_registry_bytes``.
- :func:`aggregate_statemap` — the pure merge behind the planner's
  ``GET /statemap`` and ``python -m faabric_tpu.runner.statemap``:
  per-key master host, size, access/byte totals by origin host,
  hot-key ranking, per-host mastership byte totals and the cluster
  locality ratio (local vs remote reads). Each host reports only its
  OWN accesses (the comm-matrix outbound convention), so the merge
  attributes origin without any server-side requester tracking.

Knobs: ``FAABRIC_STATE_STATS`` (``0`` disables the ledger even with
metrics on — callers then hold the shared no-op store),
``FAABRIC_STATE_MAX_KEYS`` (cardinality cap, default 256),
``FAABRIC_STATE_HALF_LIFE_S`` (estimator decay, default 120),
``FAABRIC_STATE_LOCK_STALL_MS`` (global-lock wait above this flight-
records a contention stall, default 100).
"""

from __future__ import annotations

import os
import threading
import time

from faabric_tpu.telemetry.metrics import get_metrics, metrics_enabled
from faabric_tpu.telemetry.perfprofile import DecayedStat
from faabric_tpu.util.config import _env_float, _env_int

OTHER = "other"

DEFAULT_MAX_KEYS = 256
DEFAULT_HALF_LIFE_S = 120.0
DEFAULT_LOCK_STALL_MS = 100.0

# Every op the ledger accounts; fixed upfront so the Prometheus
# counter handles are pre-built (record() is on the state hot path)
STATE_OPS = ("get", "set", "get_chunk", "set_chunk", "pull", "push_full",
             "push_partial", "append", "lock_global", "replicate")

# Snapshot lifecycle events folded into store-level estimators
SNAPSHOT_EVENTS = ("diff", "device_diff", "apply", "restore", "push")


def lock_stall_threshold_s() -> float:
    return _env_float("FAABRIC_STATE_LOCK_STALL_MS",
                      DEFAULT_LOCK_STALL_MS) / 1e3


class _KeyEntry:
    """Ledger for one state key. Updates take only this entry's lock
    (the comm-matrix per-cell discipline)."""

    __slots__ = ("ops", "bytes", "chunks", "lat", "dirty_ratio",
                 "local_reads", "remote_reads",
                 "pull_chunks_total", "pull_chunks_fresh",
                 "lock_waits", "lock_stalls", "lock_wait",
                 "master", "size", "is_master", "dirty_outstanding",
                 "backup", "epoch", "replication_lag",
                 "_lock")

    def __init__(self, half_life: float) -> None:
        self.ops: dict[str, int] = {}
        self.bytes: dict[str, int] = {}
        self.chunks: dict[str, int] = {}
        self.lat: dict[str, DecayedStat] = {}
        self.dirty_ratio = DecayedStat(half_life)
        self.local_reads = 0
        self.remote_reads = 0
        self.pull_chunks_total = 0
        self.pull_chunks_fresh = 0
        self.lock_waits = 0
        self.lock_stalls = 0
        self.lock_wait = DecayedStat(half_life)
        self.master = ""
        self.size = 0
        self.is_master = False
        self.dirty_outstanding = 0
        # Replication plane (ISSUE 19): the key's backup host, its
        # fencing epoch, and bytes acked-but-not-yet-on-the-backup
        self.backup = ""
        self.epoch = 0
        self.replication_lag = 0
        self._lock = threading.Lock()

    def add(self, op: str, nbytes: int, chunks: int, dirty_chunks: int,
            seconds: float | None, remote: bool, fresh_chunks: int | None,
            half_life: float) -> None:
        with self._lock:
            self.ops[op] = self.ops.get(op, 0) + 1
            if nbytes:
                self.bytes[op] = self.bytes.get(op, 0) + int(nbytes)
            if chunks:
                self.chunks[op] = self.chunks.get(op, 0) + int(chunks)
                if op in ("push_partial", "push_full"):
                    self.dirty_ratio.observe(
                        min(1.0, dirty_chunks / chunks))
            if op in ("get", "get_chunk", "pull"):
                if remote:
                    self.remote_reads += 1
                else:
                    self.local_reads += 1
            if op == "pull":
                self.pull_chunks_total += int(chunks)
                self.pull_chunks_fresh += int(
                    chunks if fresh_chunks is None else fresh_chunks)
            if seconds is not None and seconds > 0:
                st = self.lat.get(op)
                if st is None:
                    st = self.lat[op] = DecayedStat(half_life)
                st.observe(seconds)

    def row(self, key: str) -> dict:
        with self._lock:
            lat = {op: {"p50_ms": round(st.quantile(0.50) * 1e3, 4),
                        "p90_ms": round(st.quantile(0.90) * 1e3, 4),
                        "mean_ms": round(st.mean * 1e3, 4)}
                   for op, st in self.lat.items() if st.n}
            return {
                "key": key,
                "master": self.master,
                "backup": self.backup,
                "epoch": self.epoch,
                "replication_lag": self.replication_lag,
                "size": self.size,
                "is_master": self.is_master,
                "ops": dict(self.ops),
                "bytes": dict(self.bytes),
                "chunks": dict(self.chunks),
                "ops_total": sum(self.ops.values()),
                "bytes_total": sum(self.bytes.values()),
                "dirty_ratio": (round(self.dirty_ratio.ewma, 4)
                                if self.dirty_ratio.n else None),
                "dirty_outstanding": self.dirty_outstanding,
                "local_reads": self.local_reads,
                "remote_reads": self.remote_reads,
                "pull_chunks_total": self.pull_chunks_total,
                "pull_chunks_fresh": self.pull_chunks_fresh,
                "lock_waits": self.lock_waits,
                "lock_stalls": self.lock_stalls,
                "lock_wait_p90_ms": (
                    round(self.lock_wait.quantile(0.90) * 1e3, 4)
                    if self.lock_wait.n else None),
                "lat": lat,
            }


class _NullStateStats:
    """Shared no-op ledger while metrics / the state plane is off.
    Signatures mirror :class:`StateStatsStore` exactly — a metrics-off
    TypeError would kill a state hot path."""

    __slots__ = ()
    enabled = False

    def note_key(self, full_key, master="", size=0,
                 is_master=False, backup=None, epoch=None) -> None:
        pass

    def record(self, full_key, op, nbytes=0, chunks=0, dirty_chunks=0,
               seconds=None, remote=False, fresh_chunks=None) -> None:
        pass

    def lock_wait(self, full_key, seconds, stalled=False) -> None:
        pass

    def set_dirty_outstanding(self, full_key, n) -> None:
        pass

    def set_replication_lag(self, full_key, nbytes) -> None:
        pass

    def snapshot_event(self, kind, nbytes=0, pages=0, regions=0,
                       seconds=None) -> None:
        pass

    def set_registry_bytes(self, nbytes) -> None:
        pass

    def snapshot(self) -> dict:
        return {}

    def cardinality(self) -> int:
        return 0


NULL_STATE_STATS = _NullStateStats()


class StateStatsStore:
    """Per-key access ledger of THIS process's state traffic plus
    store-level snapshot lifecycle estimators. Keys are the full
    ``user/key`` names; the reporting host is implicit (the planner
    tags rows when aggregating, the comm-matrix/perf convention)."""

    # Concurrency contract (tools/concheck.py): the key registry
    # mutates under _lock; per-key stats under the entry's own lock.
    # NOT listed: _fast — the record-hot-path cache, WRITTEN only
    # under _lock but deliberately read lock-free (GIL-atomic
    # dict.get; a racing reader at worst misses and takes the locked
    # slow path) — the exact PerfProfileStore._fast discipline.
    GUARDS = {
        "_entries": "_lock",
        "_snap": "_lock",
        "_registry_bytes": "_lock",
    }

    enabled = True

    def __init__(self, half_life: float | None = None,
                 max_keys: int | None = None) -> None:
        self.half_life = (half_life if half_life is not None else
                          _env_float("FAABRIC_STATE_HALF_LIFE_S",
                                     DEFAULT_HALF_LIFE_S))
        self.max_keys = (max_keys if max_keys is not None else
                         _env_int("FAABRIC_STATE_MAX_KEYS",
                                  DEFAULT_MAX_KEYS))
        self._lock = threading.Lock()
        self._entries: dict[str, _KeyEntry] = {}
        # key → entry, read lock-free on the record hot path
        self._fast: dict[str, _KeyEntry] = {}
        # snapshot-lifecycle estimators: kind → {events, bytes, pages,
        # regions, lat DecayedStat}
        self._snap: dict[str, dict] = {}
        self._registry_bytes = 0
        metrics = get_metrics()
        self._op_counters = {
            op: metrics.counter(
                "faabric_state_ops_total",
                "State ops performed by this process, by op kind",
                op=op)
            for op in STATE_OPS}
        self._byte_counters = {
            op: metrics.counter(
                "faabric_state_bytes_total",
                "State bytes moved by this process, by op kind",
                op=op)
            for op in STATE_OPS}
        self._lock_stall_counter = metrics.counter(
            "faabric_state_lock_stalls_total",
            "Global-lock waits above FAABRIC_STATE_LOCK_STALL_MS")
        self._snap_event_counters = {
            kind: metrics.counter(
                "faabric_snapshot_events_total",
                "Snapshot lifecycle events, by kind", kind=kind)
            for kind in SNAPSHOT_EVENTS}
        self._snap_byte_counters = {
            kind: metrics.counter(
                "faabric_snapshot_bytes_total",
                "Snapshot diff/apply/push bytes, by kind", kind=kind)
            for kind in SNAPSHOT_EVENTS}
        self._dirty_page_counter = metrics.counter(
            "faabric_snapshot_dirty_pages_total",
            "Dirty pages evaluated across snapshot diffs")
        self._register_gauges()

    # -- hot path -------------------------------------------------------
    def _entry(self, full_key: str) -> _KeyEntry:
        entry = self._fast.get(full_key)
        if entry is not None:
            return entry
        with self._lock:
            # Exact key first: a capped store must keep feeding keys
            # that already own an entry
            entry = self._entries.get(full_key)
            if entry is None:
                key = full_key
                if len(self._entries) >= self.max_keys:
                    key = OTHER
                entry = self._entries.get(key)
                if entry is None:
                    entry = self._entries[key] = _KeyEntry(self.half_life)
            if len(self._fast) >= 8 * self.max_keys:
                # Backstop mirroring the cap: churning key names must
                # not grow the lock-free cache without bound
                self._fast.clear()
            self._fast[full_key] = entry
        return entry

    def note_key(self, full_key: str, master: str = "", size: int = 0,
                 is_master: bool = False, backup: str | None = None,
                 epoch: int | None = None) -> None:
        """Identity facts stamped at KV creation (master host, declared
        size) — the statemap's placement columns. ``backup``/``epoch``
        use None as "unchanged": "" and 0 are real values (no backup,
        unfenced) a failover re-resolve must be able to write."""
        entry = self._entry(full_key)
        with entry._lock:
            if master:
                entry.master = master
            if size:
                entry.size = int(size)
            entry.is_master = entry.is_master or is_master
            if backup is not None:
                entry.backup = backup
            if epoch is not None:
                entry.epoch = max(entry.epoch, int(epoch))

    def record(self, full_key: str, op: str, nbytes: int = 0,
               chunks: int = 0, dirty_chunks: int = 0,
               seconds: float | None = None, remote: bool = False,
               fresh_chunks: int | None = None) -> None:
        entry = self._entry(full_key)
        entry.add(op, nbytes, chunks, dirty_chunks, seconds, remote,
                  fresh_chunks, self.half_life)
        c = self._op_counters.get(op)
        if c is not None:
            c.inc()
            if nbytes:
                self._byte_counters[op].inc(int(nbytes))

    def lock_wait(self, full_key: str, seconds: float,
                  stalled: bool = False) -> None:
        entry = self._entry(full_key)
        with entry._lock:
            entry.lock_waits += 1
            entry.lock_wait.observe(max(0.0, seconds))
            if stalled:
                entry.lock_stalls += 1
        if stalled:
            self._lock_stall_counter.inc()

    def set_dirty_outstanding(self, full_key: str, n: int) -> None:
        entry = self._entry(full_key)
        with entry._lock:
            entry.dirty_outstanding = int(n)

    def set_replication_lag(self, full_key: str, nbytes: int) -> None:
        """Bytes acked to clients but not yet applied on the backup
        (0 in steady state; == size right after a promotion until
        anti-entropy lands; == size permanently while unreplicated)."""
        entry = self._entry(full_key)
        with entry._lock:
            entry.replication_lag = int(nbytes)

    # -- snapshot lifecycle ---------------------------------------------
    def snapshot_event(self, kind: str, nbytes: int = 0, pages: int = 0,
                       regions: int = 0,
                       seconds: float | None = None) -> None:
        with self._lock:
            s = self._snap.get(kind)
            if s is None:
                s = self._snap[kind] = {
                    "events": 0, "bytes": 0, "pages": 0, "regions": 0,
                    "lat": DecayedStat(self.half_life)}
            s["events"] += 1
            s["bytes"] += int(nbytes)
            s["pages"] += int(pages)
            s["regions"] += int(regions)
            if seconds is not None and seconds > 0:
                s["lat"].observe(seconds)
        c = self._snap_event_counters.get(kind)
        if c is not None:
            c.inc()
            if nbytes:
                self._snap_byte_counters[kind].inc(int(nbytes))
        if pages:
            self._dirty_page_counter.inc(int(pages))

    def set_registry_bytes(self, nbytes: int) -> None:
        with self._lock:
            self._registry_bytes = int(nbytes)

    # -- gauges ---------------------------------------------------------
    def _register_gauges(self) -> None:
        try:
            from faabric_tpu.telemetry.timeseries import get_timeseries

            ts = get_timeseries()
            ts.register("state_resident_bytes", self._resident_bytes)
            ts.register("state_dirty_chunks", self._dirty_chunks)
            ts.register("snapshot_registry_bytes",
                        self._snapshot_registry_bytes)
        except Exception:  # noqa: BLE001 — gauges are best-effort
            pass

    def _resident_bytes(self) -> float:
        with self._lock:
            entries = list(self._entries.values())
        return float(sum(e.size for e in entries if e.is_master))

    def _dirty_chunks(self) -> float:
        with self._lock:
            entries = list(self._entries.values())
        return float(sum(e.dirty_outstanding for e in entries))

    def _snapshot_registry_bytes(self) -> float:
        with self._lock:
            return float(self._registry_bytes)

    # -- export ---------------------------------------------------------
    def cardinality(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> dict:
        """JSON-safe wire form riding GET_TELEMETRY's ``statestats``
        block."""
        with self._lock:
            items = list(self._entries.items())
            snap = {kind: {"events": s["events"], "bytes": s["bytes"],
                           "pages": s["pages"], "regions": s["regions"],
                           "p50_ms": round(
                               s["lat"].quantile(0.50) * 1e3, 4),
                           "p90_ms": round(
                               s["lat"].quantile(0.90) * 1e3, 4)}
                    for kind, s in self._snap.items()}
            registry_bytes = self._registry_bytes
        rows = [e.row(k) for k, e in items]
        rows.sort(key=lambda r: -(r["bytes_total"] or 0))
        return {"keys": rows, "snapshots": snap,
                "registry_bytes": registry_bytes,
                "max_keys": self.max_keys}

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()
            self._fast.clear()
            self._snap.clear()
            self._registry_bytes = 0


# ---------------------------------------------------------------------------
# Cluster state map (pure merge — planner /statemap + runner CLI + doctor)
# ---------------------------------------------------------------------------

def aggregate_statemap(tel: dict) -> dict:
    """The cluster state map from a ``collect_telemetry()`` result.

    Each host's ledger reports only its OWN accesses, so per-origin
    attribution is the merge itself: host A's row for key K *is* the
    (K, origin=A) cell. The master column comes from the row whose
    reporter holds mastership (``is_master``), falling back to any
    reported master field."""
    keys: dict[str, dict] = {}
    hosts: dict[str, dict] = {}
    snap_events: dict[str, dict] = {}
    registry_bytes: dict[str, int] = {}
    for host, t in (tel or {}).items():
        block = (t or {}).get("statestats") or {}
        h = hosts.setdefault(host, {
            "mastered_keys": 0, "mastered_bytes": 0,
            "origin_ops": 0, "origin_bytes": 0})
        if block.get("registry_bytes"):
            registry_bytes[host] = int(block["registry_bytes"])
        for kind, s in (block.get("snapshots") or {}).items():
            agg = snap_events.setdefault(
                kind, {"events": 0, "bytes": 0, "pages": 0})
            agg["events"] += s.get("events", 0)
            agg["bytes"] += s.get("bytes", 0)
            agg["pages"] += s.get("pages", 0)
        for row in block.get("keys") or []:
            key = row.get("key") or OTHER
            agg = keys.setdefault(key, {
                "key": key, "master": "", "backup": "", "epoch": 0,
                "replication_lag": 0, "size": 0,
                "ops_total": 0, "bytes_total": 0,
                "local_reads": 0, "remote_reads": 0,
                "pull_chunks_total": 0, "pull_chunks_fresh": 0,
                "lock_waits": 0, "lock_stalls": 0,
                "by_origin": {},
            })
            if row.get("is_master") and host != OTHER:
                agg["master"] = host
                # Backup/lag are master-authored facts: only the master
                # forwards, so only its row can say where and how far
                # behind (other hosts' rows carry stale claim-time data)
                if row.get("backup") is not None:
                    agg["backup"] = row["backup"]
                agg["replication_lag"] = row.get("replication_lag") or 0
            elif not agg["master"] and row.get("master"):
                agg["master"] = row["master"]
                if not agg["backup"]:
                    agg["backup"] = row.get("backup") or ""
            agg["epoch"] = max(agg["epoch"], row.get("epoch") or 0)
            agg["size"] = max(agg["size"], row.get("size") or 0)
            agg["ops_total"] += row.get("ops_total") or 0
            agg["bytes_total"] += row.get("bytes_total") or 0
            agg["local_reads"] += row.get("local_reads") or 0
            agg["remote_reads"] += row.get("remote_reads") or 0
            agg["pull_chunks_total"] += row.get("pull_chunks_total") or 0
            agg["pull_chunks_fresh"] += row.get("pull_chunks_fresh") or 0
            agg["lock_waits"] += row.get("lock_waits") or 0
            agg["lock_stalls"] += row.get("lock_stalls") or 0
            agg["by_origin"][host] = {
                "ops": row.get("ops_total") or 0,
                "bytes": row.get("bytes_total") or 0,
            }
            h["origin_ops"] += row.get("ops_total") or 0
            h["origin_bytes"] += row.get("bytes_total") or 0
    for agg in keys.values():
        fresh = agg["pull_chunks_fresh"]
        agg["pull_amplification"] = (
            round(agg["pull_chunks_total"] / fresh, 3) if fresh else None)
        reads = agg["local_reads"] + agg["remote_reads"]
        agg["locality"] = (round(agg["local_reads"] / reads, 4)
                           if reads else None)
        master = agg["master"]
        if master in hosts:
            hosts[master]["mastered_keys"] += 1
            hosts[master]["mastered_bytes"] += agg["size"]
    ranked = sorted(keys.values(),
                    key=lambda r: (-r["bytes_total"], -r["ops_total"],
                                   r["key"]))
    for i, r in enumerate(ranked):
        r["rank"] = i + 1
    local = sum(r["local_reads"] for r in ranked)
    remote = sum(r["remote_reads"] for r in ranked)
    return {
        "generated_at": time.time(),
        "keys": ranked,
        "hosts": hosts,
        "snapshots": snap_events,
        "registry_bytes": registry_bytes,
        "locality_ratio": (round(local / (local + remote), 4)
                           if local + remote else None),
    }


def merge_placement(doc: dict, placement: dict) -> dict:
    """Overlay the planner's authoritative (master, backup, epoch) table
    onto an aggregated statemap. Host ledgers only know placements as of
    their last claim; the planner's journal is the source of truth right
    after a failover, so its values win. Keys the planner tracks but no
    ledger reported yet (e.g. promoted before any post-failover access)
    gain a zero-traffic row rather than being dropped."""
    if not placement:
        return doc
    by_key = {r["key"]: r for r in (doc.get("keys") or [])}
    for full, p in placement.items():
        row = by_key.get(full)
        if row is None:
            row = {
                "key": full, "master": "", "backup": "", "epoch": 0,
                "replication_lag": 0, "size": 0,
                "ops_total": 0, "bytes_total": 0,
                "local_reads": 0, "remote_reads": 0,
                "pull_chunks_total": 0, "pull_chunks_fresh": 0,
                "lock_waits": 0, "lock_stalls": 0,
                "by_origin": {}, "pull_amplification": None,
                "locality": None, "rank": len(by_key) + 1,
            }
            by_key[full] = row
            doc.setdefault("keys", []).append(row)
        row["master"] = p.get("master") or row["master"]
        row["backup"] = p.get("backup", row["backup"])
        row["epoch"] = max(row.get("epoch") or 0,
                           int(p.get("epoch") or 0))
    return doc


def render_statemap(doc: dict, top: int = 20) -> str:
    """Terminal table of a :func:`aggregate_statemap` document — the
    ``python -m faabric_tpu.runner.statemap`` surface."""
    keys = (doc or {}).get("keys") or []
    hosts = (doc or {}).get("hosts") or {}
    lines = [f"{'#':>3} {'key':<28} {'master':<12} {'backup':<12} "
             f"{'ep':>3} {'lag':>9} {'size':>10} "
             f"{'ops':>8} {'bytes':>12} {'local%':>7} {'pull amp':>8} "
             f"{'lock waits':>10}",
             "-" * 126]
    for r in keys[:top]:
        loc = r.get("locality")
        amp = r.get("pull_amplification")
        lines.append(
            f"{r.get('rank', 0):>3} {r.get('key', '')[:28]:<28} "
            f"{(r.get('master') or '?')[:12]:<12} "
            f"{(r.get('backup') or '-')[:12]:<12} "
            f"{r.get('epoch', 0):>3} "
            f"{r.get('replication_lag', 0):>9} "
            f"{r.get('size', 0):>10} {r.get('ops_total', 0):>8} "
            f"{r.get('bytes_total', 0):>12} "
            f"{(f'{loc * 100:.0f}%' if loc is not None else '-'):>7} "
            f"{(f'{amp:.1f}x' if amp else '-'):>8} "
            f"{r.get('lock_waits', 0):>10}")
    if len(keys) > top:
        lines.append(f"  ... {len(keys) - top} more key(s)")
    lines.append("")
    lines.append(f"{'host':<16} {'mastered keys':>13} "
                 f"{'mastered bytes':>14} {'origin bytes':>13}")
    lines.append("-" * 60)
    for host in sorted(hosts):
        h = hosts[host]
        lines.append(f"{host[:16]:<16} {h.get('mastered_keys', 0):>13} "
                     f"{h.get('mastered_bytes', 0):>14} "
                     f"{h.get('origin_bytes', 0):>13}")
    ratio = (doc or {}).get("locality_ratio")
    lines.append("")
    lines.append("cluster locality ratio: "
                 + (f"{ratio * 100:.1f}% local reads"
                    if ratio is not None else "no reads recorded"))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Singletons
# ---------------------------------------------------------------------------

def _plane_enabled() -> bool:
    return (metrics_enabled()
            and os.environ.get("FAABRIC_STATE_STATS", "1")
            not in ("0", "false", "off"))


_store: StateStatsStore | None = None
_singleton_lock = threading.Lock()


def get_state_stats() -> StateStatsStore | _NullStateStats:
    if not _plane_enabled():
        return NULL_STATE_STATS
    global _store
    if _store is None:
        with _singleton_lock:
            if _store is None:
                _store = StateStatsStore()
    return _store


def statestats_telemetry_block() -> dict:
    """The ``statestats`` block riding GET_TELEMETRY (and the planner's
    own entry): this process's per-key ledger."""
    store = get_state_stats()
    if not store.enabled:
        return {}
    return store.snapshot()


def reset_state_stats() -> None:
    """Test hook: drop the singleton so the next use re-reads env."""
    global _store
    with _singleton_lock:
        if _store is not None:
            try:
                from faabric_tpu.telemetry.timeseries import get_timeseries

                ts = get_timeseries()
                ts.unregister("state_resident_bytes",
                              _store._resident_bytes)
                ts.unregister("state_dirty_chunks", _store._dirty_chunks)
                ts.unregister("snapshot_registry_bytes",
                              _store._snapshot_registry_bytes)
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass
        _store = None
