"""Per-process resource collector (ISSUE 14 satellite): ``/proc/self``-
fed RSS/CPU/thread/fd/GC gauges on every host's ``/metrics``.

Until this PR not even the planner reported its own RSS — a leaking
control plane was invisible to the very scrape surface built to watch
the cluster. The collector reads ``/proc/self/status`` (VmRSS),
``/proc/self/stat`` (utime+stime → CPU%% between refreshes),
``/proc/self/fd`` (open descriptors), ``threading.active_count`` and
``gc`` counters, publishes them as ``faabric_process_*`` gauges in the
local metrics registry (so they ride GET_TELEMETRY to the planner's
merged ``/metrics`` with a ``host`` label), and returns the same values
as a dict for the time-series ring.

``refresh()`` throttles to one ``/proc`` read per
``MIN_REFRESH_S`` (0.2 s): the ring samples several series per tick and
must not pay five reads for one instant. Non-Linux / unreadable
``/proc`` degrades to the Python-visible subset (threads, GC) — never
raises.
"""

from __future__ import annotations

import gc
import os
import threading
import time

from faabric_tpu.telemetry.metrics import get_metrics, metrics_enabled

_CLK_TCK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100


class _NullProcStats:
    __slots__ = ()
    enabled = False

    def refresh(self) -> dict:
        return {}


NULL_PROC_STATS = _NullProcStats()


class ProcStats:
    MIN_REFRESH_S = 0.2

    # Concurrency contract (tools/concheck.py): the throttle clock, the
    # cached sample and the CPU baseline mutate under one leaf lock;
    # the /proc reads run outside it.
    GUARDS = {
        "_last_refresh": "_lock",
        "_last_values": "_lock",
        "_cpu_baseline": "_lock",
    }

    enabled = True

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._last_refresh = 0.0
        self._last_values: dict = {}
        # (monotonic_ts, cpu_seconds) of the previous refresh
        self._cpu_baseline: tuple[float, float] | None = None
        metrics = get_metrics()
        self._g_rss = metrics.gauge(
            "faabric_process_rss_bytes", "Resident set size of this process")
        self._g_cpu = metrics.gauge(
            "faabric_process_cpu_percent",
            "CPU utilisation of this process between collector refreshes "
            "(100 = one full core)")
        self._g_threads = metrics.gauge(
            "faabric_process_threads", "Live Python threads")
        self._g_fds = metrics.gauge(
            "faabric_process_open_fds", "Open file descriptors")
        self._g_gc = metrics.gauge(
            "faabric_process_gc_collections",
            "Cumulative garbage collections across all generations")

    # -- raw reads ------------------------------------------------------
    @staticmethod
    def _read_rss_bytes() -> float | None:
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        return float(line.split()[1]) * 1024.0
        except (OSError, ValueError, IndexError):
            return None
        return None

    @staticmethod
    def _read_cpu_seconds() -> float | None:
        try:
            with open("/proc/self/stat") as f:
                fields = f.read().rsplit(")", 1)[-1].split()
            # utime/stime are fields 14/15 of the full line; after the
            # comm tail split they sit at offsets 11/12
            return (float(fields[11]) + float(fields[12])) / _CLK_TCK
        except (OSError, ValueError, IndexError):
            return None

    @staticmethod
    def _read_fd_count() -> float | None:
        try:
            return float(len(os.listdir("/proc/self/fd")))
        except OSError:
            return None

    # ------------------------------------------------------------------
    def refresh(self) -> dict:
        """Read, publish and return the current gauges (throttled;
        repeat calls inside MIN_REFRESH_S return the cached dict)."""
        now = time.monotonic()
        with self._lock:
            if (now - self._last_refresh < self.MIN_REFRESH_S
                    and self._last_values):
                return self._last_values
            self._last_refresh = now
            baseline = self._cpu_baseline
        values: dict = {}
        rss = self._read_rss_bytes()
        if rss is not None:
            values["rss_bytes"] = rss
            self._g_rss.set(rss)
        cpu_s = self._read_cpu_seconds()
        if cpu_s is not None:
            if baseline is not None and now > baseline[0]:
                pct = 100.0 * (cpu_s - baseline[1]) / (now - baseline[0])
                values["cpu_percent"] = round(max(0.0, pct), 2)
                self._g_cpu.set(values["cpu_percent"])
            with self._lock:
                self._cpu_baseline = (now, cpu_s)
        values["threads"] = float(threading.active_count())
        self._g_threads.set(values["threads"])
        fds = self._read_fd_count()
        if fds is not None:
            values["open_fds"] = fds
            self._g_fds.set(fds)
        try:
            collections = float(sum(s.get("collections", 0)
                                    for s in gc.get_stats()))
        except Exception:  # noqa: BLE001 — stats shape is interpreter-owned
            collections = 0.0
        values["gc_collections"] = collections
        self._g_gc.set(collections)
        with self._lock:
            self._last_values = values
        return values


_stats: ProcStats | None = None
_lock = threading.Lock()


def get_proc_stats() -> ProcStats | _NullProcStats:
    if not metrics_enabled():
        return NULL_PROC_STATS
    global _stats
    if _stats is None:
        with _lock:
            if _stats is None:
                _stats = ProcStats()
    return _stats


def reset_proc_stats() -> None:
    global _stats
    with _lock:
        _stats = None
