"""Planner HTTP REST API.

Reference analog: src/planner/PlannerEndpointHandler.cpp:15-422 and the
HttpMessage schema (src/planner/planner.proto:33-66). POST a JSON body
``{"http_type": <int>, "payload": <json string>}``; responses are JSON.

The reference serves this from Boost.Beast inside the planner binary; the
idiomatic Python analog is a stdlib ThreadingHTTPServer on a background
thread — the REST plane is a control surface, not a data plane.
"""

from __future__ import annotations

import enum
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from faabric_tpu.batch_scheduler import reset_batch_scheduler
from faabric_tpu.batch_scheduler.scheduler import get_batch_scheduler_mode
from faabric_tpu.batch_scheduler.decision import (
    MUST_FREEZE,
    NOT_ENOUGH_SLOTS,
    SchedulingDecision,
)
from faabric_tpu.planner.planner import Planner, get_planner
from faabric_tpu.proto import (
    BatchExecuteRequest,
    is_batch_exec_request_valid,
)
from faabric_tpu.util.config import get_system_config
from faabric_tpu.util.exec_graph import build_exec_graph
from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)


class HttpMessageType(enum.IntEnum):
    # mirror of planner.proto HttpMessage.Type
    NO_TYPE = 0
    RESET = 1
    FLUSH_AVAILABLE_HOSTS = 2
    FLUSH_EXECUTORS = 3
    FLUSH_SCHEDULING_STATE = 4
    GET_AVAILABLE_HOSTS = 5
    GET_CONFIG = 6
    GET_EXEC_GRAPH = 7
    GET_IN_FLIGHT_APPS = 8
    EXECUTE_BATCH = 10
    EXECUTE_BATCH_STATUS = 11
    PRELOAD_SCHEDULING_DECISION = 12
    SET_POLICY = 13
    GET_POLICY = 14
    SET_NEXT_EVICTED_VM = 15


class PlannerHttpEndpoint:
    def __init__(self, port: int | None = None,
                 planner: Optional[Planner] = None,
                 host: str | None = None) -> None:
        conf = get_system_config()
        self.port = port if port is not None else conf.endpoint_port
        # The REST API exposes destructive unauthenticated ops (RESET,
        # FLUSH, SET_POLICY...): bind loopback unless ENDPOINT_INTERFACE
        # explicitly widens the exposure (e.g. "0.0.0.0" for a cluster)
        self.host = (host if host is not None
                     else conf.endpoint_interface or "127.0.0.1")
        self.planner = planner or get_planner()
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def start(self) -> None:
        if self._server is not None:
            return
        endpoint = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self) -> None:  # noqa: N802 — stdlib API
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                status, payload, extra_headers = endpoint.handle(body)
                data = payload.encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for key, val in (extra_headers or {}).items():
                    self.send_header(key, val)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self) -> None:  # noqa: N802
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/metrics":
                        body = endpoint.metrics_text().encode()
                        ctype = "text/plain; version=0.0.4"
                    elif path == "/trace":
                        body = endpoint.trace_json().encode()
                        ctype = "application/json"
                    elif path == "/commmatrix":
                        body = endpoint.commmatrix_json().encode()
                        ctype = "application/json"
                    elif path == "/perf":
                        body = endpoint.perf_json().encode()
                        ctype = "application/json"
                    elif path == "/healthz":
                        body = endpoint.healthz_json().encode()
                        ctype = "application/json"
                    elif path == "/timeseries":
                        body = endpoint.timeseries_json().encode()
                        ctype = "application/json"
                    elif path == "/flight":
                        body = endpoint.flight_json().encode()
                        ctype = "application/json"
                    elif path == "/topology":
                        body = endpoint.topology_json().encode()
                        ctype = "application/json"
                    elif path == "/statemap":
                        body = endpoint.statemap_json().encode()
                        ctype = "application/json"
                    elif path == "/profile":
                        body = endpoint.profile_json().encode()
                        ctype = "application/json"
                    else:
                        body = b'{"status": "running"}'
                        ctype = "application/json"
                except Exception as e:  # noqa: BLE001 — scrape errors
                    logger.exception("HTTP GET %s failed", path)
                    body = json.dumps({"error": str(e)}).encode()
                    self.send_response(500)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # quiet
                logger.debug("http: " + fmt, *args)

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="endpoint/planner-http", daemon=True)
        self._thread.start()
        logger.debug("Planner HTTP endpoint on :%d", self.port)

    def stop(self) -> None:
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._server = None
        self._thread = None

    # ------------------------------------------------------------------
    # Telemetry export (GET /metrics, GET /trace)
    # ------------------------------------------------------------------
    def metrics_text(self) -> str:
        """Prometheus text exposition merging every registered host's
        local registry (plus the planner's own) under a ``host`` label.
        Each host's communication matrix rides along as
        ``faabric_comm_*`` families with ``src``/``dst``/``plane``
        labels (cardinality-capped at the source — commmatrix.py)."""
        from faabric_tpu.telemetry import (
            families_from_cells,
            render_snapshots,
        )

        tel = self.planner.collect_telemetry(
            blocks=("metrics", "commmatrix"))
        merged = {}
        for host, t in tel.items():
            snap = dict(t.get("metrics", {}))
            cells = (t.get("commmatrix") or {}).get("cells", [])
            snap.update(families_from_cells(cells))
            merged[host] = snap
        return render_snapshots(merged)

    def commmatrix_json(self) -> str:
        """Per-link communication matrix: every host's (src rank, dst
        rank, plane) send counters, plus a cross-host merged totals view
        (hosts only report their own outbound sends, so the merge is a
        plain sum)."""
        from faabric_tpu.telemetry import merge_cell_rows

        tel = self.planner.collect_telemetry(blocks=("commmatrix",))
        per_host = {host: (t.get("commmatrix") or {}).get("cells", [])
                    for host, t in tel.items()}
        return json.dumps({
            "hosts": per_host,
            "total": merge_cell_rows(per_host),
        })

    def perf_json(self) -> str:
        """Cluster-wide performance profile (ISSUE 12): every host's
        rolling link estimators tagged with their source host, merged
        collective phase series with cross-host critical-path and
        straggler analysis. Each aggregation is checkpointed to
        ``FAABRIC_PERF_PROFILE_DIR`` (best-effort) so the doctor — and
        the next planner — can read the last known cluster profile
        without a live scrape."""
        from faabric_tpu.telemetry import aggregate_perf, persist_cluster

        doc = aggregate_perf(
            self.planner.collect_telemetry(blocks=("perf",)))
        self.planner.note_perf_aggregation(doc)
        persist_cluster(doc)
        return json.dumps(doc)

    def healthz_json(self) -> str:
        return json.dumps(self.planner.health_summary())

    def statemap_json(self) -> str:
        """Cluster state map (ISSUE 16): every host's per-key access
        ledger merged into per-key master/size/origin rows with hot-key
        ranking, per-host mastership totals, and the cluster locality
        ratio. ISSUE 19 overlays the planner's authoritative placement
        journal (master/backup/epoch) — host ledgers lag right after a
        failover, the journal never does."""
        from faabric_tpu.telemetry import aggregate_statemap, merge_placement

        doc = aggregate_statemap(
            self.planner.collect_telemetry(blocks=("statestats",)))
        merge_placement(doc, self.planner.state_placement())
        return json.dumps(doc)

    def profile_json(self) -> str:
        """Cluster CPU profile (ISSUE 18): every host's stack-sampler
        trie merged into ranked per-host × thread-class × collapsed-
        stack rows with CPU weighting and per-process GIL pressure —
        the evidence surface for the planner-shard / native-transport
        ROADMAP items."""
        from faabric_tpu.telemetry import aggregate_profile

        doc = aggregate_profile(
            self.planner.collect_telemetry(blocks=("profile",)))
        return json.dumps(doc)

    def timeseries_json(self) -> str:
        """Cluster-merged time-series rings (ISSUE 14): every host's
        sampled gauge history keyed by host — the trend surface behind
        the doctor's queue-growth and capacity-exhaustion analyzers."""
        import time as _time

        # Blocks-narrowed scrape: a trend poll repeats continuously and
        # must not pay for every host's full metrics/comm-matrix/perf
        # payload just to discard it
        tel = self.planner.collect_telemetry(blocks=("timeseries",))
        hosts = {host: (t.get("timeseries") or {})
                 for host, t in tel.items()}
        return json.dumps({"generated_at": _time.time(), "hosts": hosts})

    def flight_json(self) -> str:
        """The planner process's LIVE flight-recorder ring (ISSUE 14
        satellite): read the black box without waiting for a crash
        dump. Workers serve the same path on their own HTTP endpoints;
        ``flightdump --url`` merges them."""
        from faabric_tpu.telemetry.flight import live_ring_doc

        return json.dumps(live_ring_doc())

    def topology_json(self) -> str:
        """Cluster topology snapshot (ISSUE 9): per-host capacity plus
        the rank→host Topology of every in-flight gang-scheduled MPI
        world — the scrape surface for dashboards and placement
        debugging (`Planner.get_cluster_topology`). ISSUE 15: each
        host's live device-plane summaries ride along under
        ``device_planes`` — executable-cache stats (entries / hits /
        compiles / compile ms) and host↔device copy accounting, so the
        doctor can attribute a first-call latency spike to a device
        compile instead of guessing."""
        doc = self.planner.get_cluster_topology()
        tel = self.planner.collect_telemetry(blocks=("device_planes",))
        doc["device_planes"] = {
            host: t.get("device_planes") or []
            for host, t in tel.items()
            if t.get("device_planes")}
        return json.dumps(doc)

    def trace_json(self) -> str:
        """Chrome trace_event JSON merging every host's span buffer onto
        one wall-clock timeline (load in chrome://tracing / Perfetto).
        Raw pids are remapped per (host, pid): containerized workers are
        routinely all pid 1, and colliding pids would collapse different
        hosts onto one Perfetto process row."""
        tel = self.planner.collect_telemetry(include_trace=True,
                                             blocks=())
        events: list = []
        pid_map: dict[tuple[str, int], int] = {}
        for host in sorted(tel):
            for e in tel[host].get("trace") or []:
                key = (host, e.get("pid", 0))
                pid = pid_map.setdefault(key, len(pid_map) + 1)
                # Copy: the planner's own events are live tracer state
                events.append({**e, "pid": pid})
        return json.dumps({"traceEvents": events, "displayTimeUnit": "ms"})

    # ------------------------------------------------------------------
    def handle(self, body: bytes) -> tuple[int, str, dict]:
        """(status_code, response_json, extra_headers) for one
        HttpMessage. Handlers may return 2- or 3-tuples; the headers
        slot carries e.g. ``Retry-After`` on a 429 shed."""
        try:
            msg = json.loads(body or b"{}")
        except json.JSONDecodeError:
            return 400, json.dumps({"error": "Bad JSON in request"}), {}
        if not isinstance(msg, dict):
            return (400,
                    json.dumps({"error": "Request body must be an object"}),
                    {})
        http_type = msg.get("http_type", int(HttpMessageType.NO_TYPE))
        payload = msg.get("payload", "")
        try:
            out = self._dispatch(http_type, payload)
        except Exception as e:  # noqa: BLE001 — REST errors cross the wire
            logger.exception("HTTP handler error (type %s)", http_type)
            return 500, json.dumps({"error": str(e)}), {}
        if len(out) == 2:
            return out[0], out[1], {}
        return out

    def _dispatch(self, http_type: int, payload: str) -> tuple[int, str]:
        planner = self.planner
        t = HttpMessageType(http_type)

        if t == HttpMessageType.RESET:
            planner.reset()
            return 200, json.dumps({"status": "reset"})

        if t == HttpMessageType.FLUSH_AVAILABLE_HOSTS:
            planner.flush_hosts()
            return 200, json.dumps({"status": "flushed hosts"})

        if t == HttpMessageType.FLUSH_EXECUTORS:
            hosts = planner.flush_all_executors()
            return 200, json.dumps({"status": "flushed executors",
                                    "hosts": hosts})

        if t == HttpMessageType.FLUSH_SCHEDULING_STATE:
            planner.flush_scheduling_state()
            return 200, json.dumps({"status": "flushed scheduling state"})

        if t == HttpMessageType.GET_AVAILABLE_HOSTS:
            hosts = [{"ip": h.ip, "slots": h.slots,
                      "usedSlots": h.used_slots, "nDevices": h.n_devices}
                     for h in planner.get_available_hosts()]
            return 200, json.dumps({"hosts": hosts})

        if t == HttpMessageType.GET_CONFIG:
            conf = get_system_config()
            return 200, json.dumps({
                "ip": conf.planner_host,
                "hostTimeout": conf.planner_host_timeout,
                "policy": get_batch_scheduler_mode(),
            })

        if t == HttpMessageType.GET_EXEC_GRAPH:
            req = json.loads(payload) if payload else {}
            app_id = req.get("app_id", 0) or req.get("appId", 0)
            msg_id = req.get("id", 0)

            def get_result(aid, mid):
                result = planner.get_message_result(aid, mid)
                if result is None:
                    raise KeyError(f"No result for msg {mid} (app {aid})")
                return result

            graph = build_exec_graph(get_result, msg_id, app_id)
            return 200, graph.to_json()

        if t == HttpMessageType.GET_IN_FLIGHT_APPS:
            return 200, json.dumps(planner.in_flight_summary())

        if t == HttpMessageType.EXECUTE_BATCH:
            req = BatchExecuteRequest.from_dict(json.loads(payload))
            if not is_batch_exec_request_valid(req):
                return 400, json.dumps({"error": "Bad BatchExecRequest"})
            # Through the invocation ingress (ISSUE 8): admission
            # control + batched scheduling ticks. Sources are tenants
            # (the request's user) — one runaway tenant sheds before it
            # can starve the others. A lone request takes the immediate
            # cutover path, so interactive latency is unchanged.
            from faabric_tpu.ingress import IngressShedError

            try:
                # Queue wait bounded to ~1s: each waiting REST request
                # parks a live ThreadingHTTPServer thread, and a full
                # cluster must answer "No available hosts" promptly
                # (pre-ingress semantics) instead of accumulating up to
                # a queue-bound's worth of parked HTTP threads
                decision = planner.ingress.submit(
                    req, source=req.user or "rest", timeout=1.0)
            except IngressShedError as e:
                # Load shedding, not failure: bounded queue + explicit
                # backpressure instead of collapse. Retry-After is the
                # backlog-scaled hint admission computed.
                return (429, json.dumps({
                    "error": "Overloaded: invocation shed",
                    "reason": e.reason,
                    "retryAfterSeconds": round(e.retry_after, 3),
                }), {"Retry-After": str(max(1, int(e.retry_after + 0.5)))})
            if decision.app_id == NOT_ENOUGH_SLOTS:
                return 500, json.dumps({"error": "No available hosts"})
            if decision.app_id == MUST_FREEZE:
                return 200, json.dumps({"appId": req.app_id,
                                        "frozen": True})
            return 200, json.dumps({"appId": req.app_id,
                                    "groupId": decision.group_id,
                                    "hosts": decision.hosts,
                                    "messageIds": decision.message_ids})

        if t == HttpMessageType.EXECUTE_BATCH_STATUS:
            req = json.loads(payload) if payload else {}
            app_id = req.get("app_id", 0) or req.get("appId", 0)
            status = planner.get_batch_results(app_id)
            return 200, json.dumps({
                "appId": status.app_id,
                "finished": status.finished,
                "expectedNumMessages": status.expected_num_messages,
                "messageResults": [m.to_dict()
                                   for m in status.message_results],
            })

        if t == HttpMessageType.PRELOAD_SCHEDULING_DECISION:
            decision = SchedulingDecision.from_dict(json.loads(payload))
            planner.preload_scheduling_decision(decision)
            return 200, json.dumps({"status": "preloaded",
                                    "appId": decision.app_id})

        if t == HttpMessageType.SET_POLICY:
            policy = payload.strip().strip('"')
            if policy not in ("bin-pack", "compact", "spot"):
                return 400, json.dumps({"error": f"Unknown policy {policy}"})
            reset_batch_scheduler(policy)
            return 200, json.dumps({"policy": policy})

        if t == HttpMessageType.GET_POLICY:
            return 200, json.dumps({"policy": get_batch_scheduler_mode()})

        if t == HttpMessageType.SET_NEXT_EVICTED_VM:
            ip = payload.strip().strip('"')
            planner.set_next_evicted_host_ips([ip] if ip else [])
            return 200, json.dumps({"nextEvictedVmIps": [ip] if ip else []})

        return 400, json.dumps({"error": f"Unsupported request type {t}"})
