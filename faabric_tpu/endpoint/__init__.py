"""Planner HTTP REST API (reference src/endpoint + PlannerEndpointHandler)."""

from faabric_tpu.endpoint.http_server import HttpMessageType, PlannerHttpEndpoint
from faabric_tpu.endpoint.worker_endpoint import WorkerHttpEndpoint

__all__ = ["HttpMessageType", "PlannerHttpEndpoint", "WorkerHttpEndpoint"]
