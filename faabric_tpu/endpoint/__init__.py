"""Planner HTTP REST API (reference src/endpoint + PlannerEndpointHandler)."""

from faabric_tpu.endpoint.http_server import HttpMessageType, PlannerHttpEndpoint

__all__ = ["HttpMessageType", "PlannerHttpEndpoint"]
