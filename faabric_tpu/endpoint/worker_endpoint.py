"""Worker HTTP endpoint.

Reference analog: src/endpoint/FaabricEndpointHandler.cpp:16-56 — the
worker's HTTP surface rejects every functional request, directing
clients to the planner, which owns the REST API. Exceptions, all
answered locally (liveness/diagnosis must not depend on the planner
being up):

- ``GET /healthz``   — identity, uptime, executor load;
- ``GET /metrics``   — this process's local registry (Prometheus text,
  including the ``faabric_process_*`` resource gauges);
- ``GET /timeseries``— this process's sampled-gauge ring (ISSUE 14);
- ``GET /flight``    — the LIVE flight-recorder ring (read the black
  box without waiting for a crash dump; ``flightdump --url`` merges).

Started by the WorkerRuntime when ``WORKER_HTTP_PORT`` (or an explicit
port) is set.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

REJECTION = json.dumps({
    "error": "Workers do not accept direct requests; use the planner's "
             "HTTP endpoint",
}).encode()


class WorkerHttpEndpoint:
    def __init__(self, port: int, runtime=None) -> None:
        self.port = port
        self.runtime = runtime
        self._started_at = time.monotonic()
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def healthz(self) -> dict:
        body = {
            "status": "ok",
            "pid": os.getpid(),
            "uptimeSeconds": round(time.monotonic() - self._started_at, 3),
        }
        rt = self.runtime
        if rt is not None:
            body["host"] = rt.host
            body["slots"] = rt.slots
            scheduler = getattr(rt, "scheduler", None)
            if scheduler is not None:
                body["executors"] = scheduler.get_executor_count()
        return body

    @staticmethod
    def metrics_text() -> str:
        from faabric_tpu.telemetry import get_metrics, get_proc_stats

        get_proc_stats().refresh()
        return get_metrics().render_prometheus()

    @staticmethod
    def timeseries_json() -> str:
        from faabric_tpu.telemetry import get_timeseries

        return json.dumps(get_timeseries().snapshot())

    @staticmethod
    def flight_json() -> str:
        from faabric_tpu.telemetry.flight import live_ring_doc

        return json.dumps(live_ring_doc())

    @staticmethod
    def profile_json() -> str:
        from faabric_tpu.telemetry import get_profiler

        return json.dumps(get_profiler().snapshot())

    def start(self) -> None:
        """Best-effort: a health probe must never take the worker down.
        A bind failure (e.g. two aliased workers on one box sharing
        WORKER_HTTP_PORT) logs a warning and disables the endpoint."""
        if self._server is not None:
            return
        endpoint = self

        class Handler(BaseHTTPRequestHandler):
            def _respond(self, status: int, body: bytes) -> None:
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _reject(self) -> None:
                self._respond(403, REJECTION)

            def do_GET(self) -> None:  # noqa: N802 — stdlib API
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/healthz":
                        self._respond(
                            200, json.dumps(endpoint.healthz()).encode())
                    elif path == "/metrics":
                        self._respond(200,
                                      endpoint.metrics_text().encode())
                    elif path == "/timeseries":
                        self._respond(200,
                                      endpoint.timeseries_json().encode())
                    elif path == "/flight":
                        self._respond(200,
                                      endpoint.flight_json().encode())
                    elif path == "/profile":
                        self._respond(200,
                                      endpoint.profile_json().encode())
                    else:
                        self._reject()
                except Exception as e:  # noqa: BLE001 — a scrape error
                    # must not kill the handler thread mid-response
                    logger.exception("worker-http GET %s failed", path)
                    self._respond(
                        500, json.dumps({"error": str(e)}).encode())

            do_POST = do_PUT = do_DELETE = _reject

            def log_message(self, fmt, *args):
                logger.debug("worker-http: " + fmt, *args)

        try:
            self._server = ThreadingHTTPServer(("0.0.0.0", self.port),
                                               Handler)
        except OSError as e:
            logger.warning("Worker /healthz endpoint on :%d unavailable "
                           "(%s); continuing without it", self.port, e)
            self._server = None
            return
        self.port = self._server.server_address[1]  # resolve port 0
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="endpoint/worker-http", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._server = None
        self._thread = None
