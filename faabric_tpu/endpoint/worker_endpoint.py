"""Worker HTTP endpoint.

Reference analog: src/endpoint/FaabricEndpointHandler.cpp:16-56 — the
worker's HTTP surface rejects every functional request, directing
clients to the planner, which owns the REST API. One exception:
``GET /healthz`` answers locally (liveness must not depend on the
planner being up), reporting the worker's identity, uptime and executor
load. Started by the WorkerRuntime when ``WORKER_HTTP_PORT`` (or an
explicit port) is set.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

REJECTION = json.dumps({
    "error": "Workers do not accept direct requests; use the planner's "
             "HTTP endpoint",
}).encode()


class WorkerHttpEndpoint:
    def __init__(self, port: int, runtime=None) -> None:
        self.port = port
        self.runtime = runtime
        self._started_at = time.monotonic()
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def healthz(self) -> dict:
        body = {
            "status": "ok",
            "pid": os.getpid(),
            "uptimeSeconds": round(time.monotonic() - self._started_at, 3),
        }
        rt = self.runtime
        if rt is not None:
            body["host"] = rt.host
            body["slots"] = rt.slots
            scheduler = getattr(rt, "scheduler", None)
            if scheduler is not None:
                body["executors"] = scheduler.get_executor_count()
        return body

    def start(self) -> None:
        """Best-effort: a health probe must never take the worker down.
        A bind failure (e.g. two aliased workers on one box sharing
        WORKER_HTTP_PORT) logs a warning and disables the endpoint."""
        if self._server is not None:
            return
        endpoint = self

        class Handler(BaseHTTPRequestHandler):
            def _respond(self, status: int, body: bytes) -> None:
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _reject(self) -> None:
                self._respond(403, REJECTION)

            def do_GET(self) -> None:  # noqa: N802 — stdlib API
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path == "/healthz":
                    self._respond(200,
                                  json.dumps(endpoint.healthz()).encode())
                else:
                    self._reject()

            do_POST = do_PUT = do_DELETE = _reject

            def log_message(self, fmt, *args):
                logger.debug("worker-http: " + fmt, *args)

        try:
            self._server = ThreadingHTTPServer(("0.0.0.0", self.port),
                                               Handler)
        except OSError as e:
            logger.warning("Worker /healthz endpoint on :%d unavailable "
                           "(%s); continuing without it", self.port, e)
            self._server = None
            return
        self.port = self._server.server_address[1]  # resolve port 0
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="worker-http", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._server = None
        self._thread = None
