"""Worker HTTP endpoint.

Reference analog: src/endpoint/FaabricEndpointHandler.cpp:16-56 — the
worker's HTTP surface deliberately rejects every request, directing
clients to the planner, which owns the REST API. Kept for wire parity
(deployments probe worker ports) and as the hook point if a direct worker
API ever returns.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from faabric_tpu.util.logging import get_logger

logger = get_logger(__name__)

REJECTION = json.dumps({
    "error": "Workers do not accept direct requests; use the planner's "
             "HTTP endpoint",
}).encode()


class WorkerHttpEndpoint:
    def __init__(self, port: int) -> None:
        self.port = port
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._server is not None:
            return

        class Handler(BaseHTTPRequestHandler):
            def _reject(self) -> None:
                self.send_response(403)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(REJECTION)))
                self.end_headers()
                self.wfile.write(REJECTION)

            do_GET = do_POST = do_PUT = do_DELETE = _reject

            def log_message(self, fmt, *args):
                logger.debug("worker-http: " + fmt, *args)

        self._server = ThreadingHTTPServer(("0.0.0.0", self.port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="worker-http", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._server = None
        self._thread = None
