"""Batch-scheduler policy layer.

Reference analog: include/faabric/batch-scheduler/BatchScheduler.h:70-131 and
src/batch-scheduler/BatchScheduler.cpp:15-45. Pure in-memory: policies map
(host map, in-flight apps, request) → SchedulingDecision and never do I/O.

All three reference policies share the same skeleton — sort the hosts by a
policy-specific criterion, then greedily fill — so the shared greedy fill
and migration-minimisation live here and policies supply the sort/compare
hooks, rather than duplicating the fill loop per policy as the reference
does.

TPU twist: a ``HostState`` advertises its chip count; slots are execution
slots, and ranks gang-scheduled onto a host are later pinned to chips
(device ids in the decision) by the planner at dispatch time.
"""

from __future__ import annotations

import dataclasses
import enum
import threading
from typing import Dict, Optional, Tuple

from faabric_tpu.batch_scheduler.decision import SchedulingDecision
from faabric_tpu.proto import BatchExecuteRequest, BatchExecuteType


@dataclasses.dataclass
class HostState:
    """One row of the planner's host map (reference BatchScheduler.h:29-41,
    plus TPU chip inventory and spot-eviction taint)."""

    ip: str
    slots: int = 0
    used_slots: int = 0
    n_devices: int = 0
    for_eviction: bool = False

    @property
    def available(self) -> int:
        return max(0, self.slots - self.used_slots)

    def claim(self, n: int) -> None:
        self.used_slots = min(self.slots, self.used_slots + n)

    def free(self, n: int) -> None:
        self.used_slots = max(0, self.used_slots - n)


HostMap = Dict[str, HostState]
# app_id → (request, decision)
InFlightReqs = Dict[int, Tuple[BatchExecuteRequest, SchedulingDecision]]


class DecisionType(enum.IntEnum):
    NO_DECISION_TYPE = 0
    NEW = 1
    DIST_CHANGE = 2
    SCALE_CHANGE = 3


def copy_host_map(host_map: HostMap) -> HostMap:
    return {ip: dataclasses.replace(h) for ip, h in host_map.items()}


def minimise_num_of_migrations(new_decision: SchedulingDecision,
                               old_decision: SchedulingDecision) -> SchedulingDecision:
    """Rewrite ``new_decision`` to keep as many messages on their old host as
    its host histogram allows, so a migration moves the fewest ranks
    (reference BinPackScheduler.cpp:26-93)."""
    out = SchedulingDecision(old_decision.app_id, old_decision.group_id)
    budget = new_decision.host_freq_count()

    assert new_decision.n_messages == old_decision.n_messages

    # Keep old placements wherever the new histogram has room for them.
    placed = [False] * old_decision.n_messages
    for i, old_host in enumerate(old_decision.hosts):
        if budget.get(old_host, 0) > 0:
            out.add_message_in_position(
                i, old_host, old_decision.message_ids[i],
                old_decision.app_idxs[i], old_decision.group_idxs[i],
                old_decision.mpi_ports[i], old_decision.device_ids[i])
            budget[old_host] -= 1
            placed[i] = True

    # Spill the rest onto whichever hosts still have histogram budget. These
    # are the actual migrations; ports/devices are assigned by the planner.
    for i in range(old_decision.n_messages):
        if placed[i]:
            continue
        next_host = next(ip for ip, n in budget.items() if n > 0)
        out.add_message_in_position(
            i, next_host, old_decision.message_ids[i],
            old_decision.app_idxs[i], old_decision.group_idxs[i], -1, -1)
        budget[next_host] -= 1

    assert all(n == 0 for n in budget.values())
    return out


class BatchScheduler:
    """Policy interface. Subclasses implement ``get_sorted_hosts`` and
    ``is_first_decision_better``; the greedy fill is shared."""

    # True only for policies whose filter_hosts() removes hosts that are
    # being taken away from the cluster (spot eviction) rather than hosts
    # that are merely ineligible for this app.
    filtered_hosts_are_evicted = False

    @staticmethod
    def get_decision_type(in_flight: InFlightReqs,
                          req: BatchExecuteRequest) -> DecisionType:
        # Reference BatchScheduler.cpp getDecisionType: NEW if the app is not
        # in flight; DIST_CHANGE for a same-size MIGRATION request;
        # SCALE_CHANGE otherwise (chaining / fork adds messages).
        if req.app_id not in in_flight:
            return DecisionType.NEW
        old_req, _ = in_flight[req.app_id]
        if (req.type == int(BatchExecuteType.MIGRATION)
                and req.n_messages() == old_req.n_messages()):
            return DecisionType.DIST_CHANGE
        return DecisionType.SCALE_CHANGE

    # ------------------------------------------------------------------
    # Policy hooks
    # ------------------------------------------------------------------
    def get_sorted_hosts(self, host_map: HostMap, in_flight: InFlightReqs,
                         req: BatchExecuteRequest,
                         decision_type: DecisionType) -> list[HostState]:
        raise NotImplementedError

    def is_first_decision_better(self, host_map: HostMap,
                                 decision_a: SchedulingDecision,
                                 decision_b: SchedulingDecision) -> bool:
        raise NotImplementedError

    def filter_hosts(self, host_map: HostMap, in_flight: InFlightReqs,
                     req: BatchExecuteRequest) -> set[str]:
        """Drop ineligible hosts before sorting; returns removed ips."""
        return set()

    # ------------------------------------------------------------------
    def make_scheduling_decision(self, host_map: HostMap,
                                 in_flight: InFlightReqs,
                                 req: BatchExecuteRequest) -> SchedulingDecision:
        from faabric_tpu.batch_scheduler.decision import (
            do_not_migrate_decision,
            must_freeze_decision,
            not_enough_slots_decision,
        )

        # Work on a copy: sorting hooks mutate slot counts (freeing the
        # migrating app's slots) and the caller's map must stay authoritative.
        host_map = copy_host_map(host_map)
        removed = self.filter_hosts(host_map, in_flight, req)

        decision_type = self.get_decision_type(in_flight, req)
        sorted_hosts = self.get_sorted_hosts(host_map, in_flight, req,
                                             decision_type)

        # An OpenMP-style request with the single-host hint only ever
        # considers the first host (reference BinPackScheduler.cpp:312-317).
        is_omp = req.n_messages() > 0 and req.messages[0].is_omp
        if req.single_host_hint and is_omp:
            sorted_hosts = sorted_hosts[:1]

        # Greedy fill: as many messages as fit per host, in sort order.
        decision = SchedulingDecision(req.app_id, 0)
        msg_idx = 0
        left = req.n_messages()
        for host in sorted_hosts:
            n_here = min(left, host.available)
            for _ in range(n_here):
                m = req.messages[msg_idx]
                decision.add_message(host.ip, m.id, m.app_idx, m.group_idx)
                msg_idx += 1
            left -= n_here
            if left == 0:
                break

        if decision_type != DecisionType.DIST_CHANGE:
            if left > 0:
                return not_enough_slots_decision()
            return decision

        # DIST_CHANGE: only migrate if the fresh decision is an improvement.
        old_decision = in_flight[req.app_id][1]
        if left > 0:
            # Only spot's filtered hosts mean "host going away": ranks there
            # with nowhere to go must freeze. Other policies filter hosts
            # that are merely off-limits for new placements (e.g. compact's
            # other-tenant hosts), where a full cluster means "don't move".
            if (self.filtered_hosts_are_evicted and removed
                    and any(h in removed for h in old_decision.hosts)):
                return must_freeze_decision()
            return not_enough_slots_decision()
        if self._should_migrate(host_map, decision, old_decision, removed):
            return minimise_num_of_migrations(decision, old_decision)
        return do_not_migrate_decision()

    def _should_migrate(self, host_map: HostMap, new_decision: SchedulingDecision,
                        old_decision: SchedulingDecision,
                        removed: set[str]) -> bool:
        return self.is_first_decision_better(host_map, new_decision, old_decision)


# ---------------------------------------------------------------------------
# Mode switch (reference src/batch-scheduler/BatchScheduler.cpp:15-45)
# ---------------------------------------------------------------------------

_scheduler: Optional[BatchScheduler] = None
_mode_override: Optional[str] = None
_scheduler_lock = threading.Lock()


def get_batch_scheduler() -> BatchScheduler:
    from faabric_tpu.batch_scheduler.bin_pack import BinPackScheduler
    from faabric_tpu.batch_scheduler.compact import CompactScheduler
    from faabric_tpu.batch_scheduler.spot import SpotScheduler
    from faabric_tpu.util.config import get_system_config

    global _scheduler
    with _scheduler_lock:
        if _scheduler is None:
            mode = _mode_override or get_system_config().batch_scheduler_mode
            if mode == "bin-pack":
                _scheduler = BinPackScheduler()
            elif mode == "compact":
                _scheduler = CompactScheduler()
            elif mode == "spot":
                _scheduler = SpotScheduler()
            else:
                raise ValueError(f"Unknown batch scheduler mode: {mode}")
        return _scheduler


def get_batch_scheduler_mode() -> str:
    """The authoritative current policy mode (override or config)."""
    from faabric_tpu.util.config import get_system_config

    with _scheduler_lock:
        return _mode_override or get_system_config().batch_scheduler_mode


def reset_batch_scheduler(new_mode: str | None = None) -> None:
    """Drop the cached policy; an explicit ``new_mode`` overrides the config
    knob for this process without touching the environment or the live
    SystemConfig (reference resetBatchScheduler(newMode))."""
    global _scheduler, _mode_override
    with _scheduler_lock:
        _scheduler = None
        _mode_override = new_mode
    # Cached placements were chosen by the old policy
    from faabric_tpu.batch_scheduler.decision_cache import get_decision_cache

    get_decision_cache().clear()
