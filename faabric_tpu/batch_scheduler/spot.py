"""Spot policy (reference src/batch-scheduler/SpotScheduler.cpp).

Bin-pack behaviour for NEW/SCALE_CHANGE, but hosts tainted for spot
eviction are never scheduled onto. A DIST_CHANGE evacuates any ranks off
to-be-evicted hosts if capacity exists elsewhere; with no capacity the whole
app MUST_FREEZE (snapshots parked on the planner until slots return).
"""

from __future__ import annotations

from faabric_tpu.batch_scheduler.decision import SchedulingDecision
from faabric_tpu.batch_scheduler.scheduler import (
    BatchScheduler,
    DecisionType,
    HostMap,
    HostState,
    InFlightReqs,
)
from faabric_tpu.batch_scheduler.bin_pack import (
    sort_hosts_by_app_freq,
    sort_hosts_larger_first,
)
from faabric_tpu.proto import BatchExecuteRequest


class SpotScheduler(BatchScheduler):
    filtered_hosts_are_evicted = True

    def filter_hosts(self, host_map: HostMap, in_flight: InFlightReqs,
                     req: BatchExecuteRequest) -> set[str]:
        # Remove the next-to-be-evicted hosts entirely (reference
        # SpotScheduler.cpp filterHosts — there tainted via MUST_EVICT_IP,
        # here via an explicit flag on HostState).
        removed = {ip for ip, h in host_map.items() if h.for_eviction}
        for ip in removed:
            del host_map[ip]
        return removed

    def get_sorted_hosts(self, host_map: HostMap, in_flight: InFlightReqs,
                         req: BatchExecuteRequest,
                         decision_type: DecisionType) -> list[HostState]:
        hosts = list(host_map.values())
        if decision_type == DecisionType.NEW:
            return sort_hosts_larger_first(hosts)

        old_decision = in_flight[req.app_id][1]
        freq = old_decision.host_freq_count()

        if decision_type == DecisionType.SCALE_CHANGE:
            return sort_hosts_by_app_freq(hosts, freq)

        # DIST_CHANGE: free the app's slots on the surviving hosts and
        # re-schedule with the bin-pack-with-freq criteria.
        for h in hosts:
            if h.ip in freq:
                h.free(freq[h.ip])
        return sort_hosts_by_app_freq(hosts, freq)

    def _should_migrate(self, host_map: HostMap, new_decision: SchedulingDecision,
                        old_decision: SchedulingDecision,
                        removed: set[str]) -> bool:
        # Only migrate if the app currently has ranks on an evicted host
        # (reference SpotScheduler.cpp:313-323).
        return any(ip in removed for ip in old_decision.hosts)

    def is_first_decision_better(self, host_map: HostMap,
                                 decision_a: SchedulingDecision,
                                 decision_b: SchedulingDecision) -> bool:
        raise NotImplementedError("SPOT migrates on eviction, not on locality")
