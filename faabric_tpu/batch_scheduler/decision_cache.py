"""Cache of scheduling decisions for repeated fork-join shapes.

Reference analog: include/faabric/batch-scheduler/DecisionCache.h:14-33.
Keyed by (user, function, batch type, message count): a runtime that
forks the same N-wide THREADS batch repeatedly reuses the host placement
instead of re-planning every time.

ISSUE 8 promoted this cache to the invocation-ingress **admission fast
path**: plain FUNCTIONS batches with a signature already seen skip the
policy run entirely inside the scheduling tick and go straight to claim
+ dispatch (planner `_decision_from_cache` still validates the cached
hosts against live capacity — a stale placement falls back to the
policy and re-caches). Hit/miss counters feed the planner's ``/healthz``
decision-cache block so the fast-path's effectiveness is observable.
"""

from __future__ import annotations

import threading
from typing import Optional

from faabric_tpu.proto import BatchExecuteRequest
from faabric_tpu.telemetry import get_metrics

_metrics = get_metrics()
_HITS = _metrics.counter(
    "faabric_decision_cache_hits_total",
    "Scheduling decisions served from the decision cache (policy run "
    "skipped)")
_MISSES = _metrics.counter(
    "faabric_decision_cache_misses_total",
    "Decision-cache lookups that fell through to the policy (absent "
    "signature or stale capacity)")


class CachedDecision:
    """Cached placement. Unlike the reference, the group id is NOT reused
    across forks — this framework mints a fresh group id per app so PTP
    state can be garbage-collected per app; only hosts are recycled."""

    def __init__(self, hosts: list[str], group_id: int = 0) -> None:
        self._hosts = hosts
        self._group_id = group_id

    @property
    def hosts(self) -> list[str]:
        return list(self._hosts)

    @property
    def group_id(self) -> int:
        return self._group_id


class DecisionCache:
    # Concurrency contract (tools/concheck.py): map + counters under the
    # cache's own leaf lock.
    GUARDS = {"_cache": "_lock", "_hits": "_lock", "_misses": "_lock"}

    def __init__(self) -> None:
        self._cache: dict[str, CachedDecision] = {}
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0

    @staticmethod
    def _key(req: BatchExecuteRequest) -> str:
        # The full fork signature: user/function, batch TYPE, tenant tag
        # and width. Type matters since ISSUE 8 — a THREADS fork and a
        # FUNCTIONS invocation of the same function must never share a
        # placement row (their scheduling semantics differ) — and so does
        # subtype: the compact policy uses it as a tenant id, and two
        # tenants must never collide onto one cached placement.
        return (f"{req.user}/{req.function}:{req.type}:{req.subtype}:"
                f"{req.n_messages()}")

    def get_cached_decision(self, req: BatchExecuteRequest) -> Optional[CachedDecision]:
        with self._lock:
            return self._cache.get(self._key(req))

    def add_cached_decision(self, req: BatchExecuteRequest, hosts: list[str],
                            group_id: int) -> None:
        if len(hosts) != req.n_messages():
            raise ValueError(
                f"Cached hosts ({len(hosts)}) != messages ({req.n_messages()})"
            )
        with self._lock:
            self._cache[self._key(req)] = CachedDecision(hosts, group_id)

    def record_outcome(self, hit: bool) -> None:
        """Count one admission fast-path lookup outcome (a capacity-
        invalidated entry counts as a miss — the policy ran)."""
        with self._lock:
            if hit:
                self._hits += 1
            else:
                self._misses += 1
        (_HITS if hit else _MISSES).inc()

    def stats(self) -> dict:
        with self._lock:
            total = self._hits + self._misses
            return {
                "entries": len(self._cache),
                "hits": self._hits,
                "misses": self._misses,
                "hitRate": round(self._hits / total, 4) if total else 0.0,
            }

    def clear(self) -> None:
        with self._lock:
            self._cache.clear()


_cache: Optional[DecisionCache] = None
_cache_lock = threading.Lock()


def get_decision_cache() -> DecisionCache:
    global _cache
    if _cache is None:
        with _cache_lock:
            if _cache is None:
                _cache = DecisionCache()
    return _cache
