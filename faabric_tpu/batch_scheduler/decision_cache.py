"""Cache of scheduling decisions for repeated fork-join shapes.

Reference analog: include/faabric/batch-scheduler/DecisionCache.h:14-33.
Keyed by (user, function, message count): a runtime that forks the same
N-wide THREADS batch repeatedly reuses the group id and host placement
instead of re-planning every time.
"""

from __future__ import annotations

import threading
from typing import Optional

from faabric_tpu.proto import BatchExecuteRequest


class CachedDecision:
    """Cached placement. Unlike the reference, the group id is NOT reused
    across forks — this framework mints a fresh group id per app so PTP
    state can be garbage-collected per app; only hosts are recycled."""

    def __init__(self, hosts: list[str], group_id: int = 0) -> None:
        self._hosts = hosts
        self._group_id = group_id

    @property
    def hosts(self) -> list[str]:
        return list(self._hosts)

    @property
    def group_id(self) -> int:
        return self._group_id


class DecisionCache:
    def __init__(self) -> None:
        self._cache: dict[str, CachedDecision] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _key(req: BatchExecuteRequest) -> str:
        return f"{req.user}/{req.function}:{req.n_messages()}"

    def get_cached_decision(self, req: BatchExecuteRequest) -> Optional[CachedDecision]:
        with self._lock:
            return self._cache.get(self._key(req))

    def add_cached_decision(self, req: BatchExecuteRequest, hosts: list[str],
                            group_id: int) -> None:
        if len(hosts) != req.n_messages():
            raise ValueError(
                f"Cached hosts ({len(hosts)}) != messages ({req.n_messages()})"
            )
        with self._lock:
            self._cache[self._key(req)] = CachedDecision(hosts, group_id)

    def clear(self) -> None:
        with self._lock:
            self._cache.clear()


_cache: Optional[DecisionCache] = None
_cache_lock = threading.Lock()


def get_decision_cache() -> DecisionCache:
    global _cache
    if _cache is None:
        with _cache_lock:
            if _cache is None:
                _cache = DecisionCache()
    return _cache
