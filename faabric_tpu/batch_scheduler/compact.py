"""Compact policy (reference src/batch-scheduler/CompactScheduler.cpp).

NEW/SCALE_CHANGE behave like bin-pack; DIST_CHANGE consolidates: re-schedule
into the *fullest* hosts and migrate only if that frees at least one whole
host. Also filters out hosts running other tenants' apps (the reference
wedges a user id into the request subtype for multi-tenant simulations).
"""

from __future__ import annotations

from faabric_tpu.batch_scheduler.decision import SchedulingDecision
from faabric_tpu.batch_scheduler.scheduler import (
    BatchScheduler,
    DecisionType,
    HostMap,
    HostState,
    InFlightReqs,
    copy_host_map,
)
from faabric_tpu.batch_scheduler.bin_pack import (
    sort_hosts_by_app_freq,
    sort_hosts_larger_first,
)
from faabric_tpu.proto import BatchExecuteRequest


class CompactScheduler(BatchScheduler):
    def filter_hosts(self, host_map: HostMap, in_flight: InFlightReqs,
                     req: BatchExecuteRequest) -> set[str]:
        # Hosts running apps of a different tenant are off-limits
        # (reference CompactScheduler.cpp filterHosts).
        removed: set[str] = set()
        for other_req, other_decision in in_flight.values():
            if other_req.subtype == req.subtype:
                continue
            for ip in other_decision.hosts:
                if ip in host_map:
                    del host_map[ip]
                    removed.add(ip)
        return removed

    def get_sorted_hosts(self, host_map: HostMap, in_flight: InFlightReqs,
                         req: BatchExecuteRequest,
                         decision_type: DecisionType) -> list[HostState]:
        hosts = list(host_map.values())
        if decision_type == DecisionType.NEW:
            return sort_hosts_larger_first(hosts)

        old_decision = in_flight[req.app_id][1]
        freq = old_decision.host_freq_count()

        if decision_type == DecisionType.SCALE_CHANGE:
            return sort_hosts_by_app_freq(hosts, freq)

        # DIST_CHANGE: free the app's slots, then pack into the FULLEST
        # hosts first so holes are filled and whole hosts drain empty.
        for h in hosts:
            if h.ip in freq:
                h.free(freq[h.ip])
        return sorted(hosts, key=lambda h: (h.used_slots, h.slots, h.ip),
                      reverse=True)

    def is_first_decision_better(self, host_map: HostMap,
                                 decision_a: SchedulingDecision,
                                 decision_b: SchedulingDecision) -> bool:
        """Better = more completely-free hosts after applying the decision
        (reference CompactScheduler.cpp:115-172). ``host_map`` arrives with
        the app's old slots already freed, so each candidate is applied on
        top of it."""

        def n_free_hosts_with(decision: SchedulingDecision) -> int:
            trial = copy_host_map(host_map)
            for ip in decision.hosts:
                if ip in trial:
                    trial[ip].claim(1)
            return sum(1 for h in trial.values() if h.used_slots == 0)

        return n_free_hosts_with(decision_a) > n_free_hosts_with(decision_b)
