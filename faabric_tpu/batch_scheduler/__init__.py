"""Pluggable batch-scheduling policies (reference src/batch-scheduler)."""

from faabric_tpu.batch_scheduler.decision import (
    DO_NOT_MIGRATE,
    MUST_FREEZE,
    NOT_ENOUGH_SLOTS,
    SchedulingDecision,
    do_not_migrate_decision,
    is_sentinel_decision,
    must_freeze_decision,
    not_enough_slots_decision,
)
from faabric_tpu.batch_scheduler.decision_cache import (
    CachedDecision,
    DecisionCache,
    get_decision_cache,
)
from faabric_tpu.batch_scheduler.scheduler import (
    BatchScheduler,
    DecisionType,
    HostMap,
    HostState,
    InFlightReqs,
    copy_host_map,
    get_batch_scheduler,
    minimise_num_of_migrations,
    reset_batch_scheduler,
)
from faabric_tpu.batch_scheduler.bin_pack import BinPackScheduler, locality_score
from faabric_tpu.batch_scheduler.compact import CompactScheduler
from faabric_tpu.batch_scheduler.spot import SpotScheduler

__all__ = [
    "DO_NOT_MIGRATE",
    "MUST_FREEZE",
    "NOT_ENOUGH_SLOTS",
    "BatchScheduler",
    "BinPackScheduler",
    "CachedDecision",
    "CompactScheduler",
    "DecisionCache",
    "DecisionType",
    "HostMap",
    "HostState",
    "InFlightReqs",
    "SchedulingDecision",
    "SpotScheduler",
    "copy_host_map",
    "do_not_migrate_decision",
    "get_batch_scheduler",
    "get_decision_cache",
    "is_sentinel_decision",
    "locality_score",
    "minimise_num_of_migrations",
    "must_freeze_decision",
    "not_enough_slots_decision",
    "reset_batch_scheduler",
]
