"""Scheduling decisions.

Reference analog: include/faabric/batch-scheduler/SchedulingDecision.h:190-250
and src/batch-scheduler/SchedulingDecision.cpp. A decision is a set of
parallel per-message vectors (host, message id, app idx, group idx, MPI port)
— extended here with a per-message **device id**: the TPU chip on the chosen
host a gang-scheduled rank is pinned to, so MPI worlds map ranks onto an ICI
mesh directly from the decision.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable

# Sentinel app/group ids (reference BatchScheduler.h:8-19)
DO_NOT_MIGRATE = -98
NOT_ENOUGH_SLOTS = -99
MUST_FREEZE = -97


@dataclasses.dataclass
class SchedulingDecision:
    app_id: int
    group_id: int = 0

    hosts: list[str] = dataclasses.field(default_factory=list)
    message_ids: list[int] = dataclasses.field(default_factory=list)
    app_idxs: list[int] = dataclasses.field(default_factory=list)
    group_idxs: list[int] = dataclasses.field(default_factory=list)
    mpi_ports: list[int] = dataclasses.field(default_factory=list)
    device_ids: list[int] = dataclasses.field(default_factory=list)

    # ------------------------------------------------------------------
    @property
    def n_messages(self) -> int:
        return len(self.hosts)

    def is_single_host(self) -> bool:
        return len(set(self.hosts)) <= 1

    def clone(self) -> "SchedulingDecision":
        """Independent snapshot of the placement vectors. The planner
        keeps mutating ITS copy as results land (remove_message), so
        anything handed to a caller must be detached first."""
        return SchedulingDecision(
            app_id=self.app_id, group_id=self.group_id,
            hosts=list(self.hosts), message_ids=list(self.message_ids),
            app_idxs=list(self.app_idxs), group_idxs=list(self.group_idxs),
            mpi_ports=list(self.mpi_ports),
            device_ids=list(self.device_ids))

    def unique_hosts(self) -> list[str]:
        seen: dict[str, None] = {}
        for h in self.hosts:
            seen.setdefault(h)
        return list(seen)

    def add_message(self, host: str, message_id: int, app_idx: int,
                    group_idx: int, mpi_port: int = 0, device_id: int = -1) -> None:
        self.hosts.append(host)
        self.message_ids.append(message_id)
        self.app_idxs.append(app_idx)
        self.group_idxs.append(group_idx)
        self.mpi_ports.append(mpi_port)
        self.device_ids.append(device_id)

    def add_message_in_position(self, idx: int, host: str, message_id: int,
                                app_idx: int, group_idx: int,
                                mpi_port: int = 0, device_id: int = -1) -> None:
        """Place a message at a fixed index, growing with empty slots as
        needed (reference SchedulingDecision.h addMessageInPosition)."""
        while self.n_messages <= idx:
            self.add_message("", 0, 0, 0, 0, -1)
        self.hosts[idx] = host
        self.message_ids[idx] = message_id
        self.app_idxs[idx] = app_idx
        self.group_idxs[idx] = group_idx
        self.mpi_ports[idx] = mpi_port
        self.device_ids[idx] = device_id

    def remove_message(self, message_id: int) -> None:
        try:
            i = self.message_ids.index(message_id)
        except ValueError:
            return
        for vec in (self.hosts, self.message_ids, self.app_idxs,
                    self.group_idxs, self.mpi_ports, self.device_ids):
            del vec[i]

    def host_for_idx(self, group_idx: int) -> str:
        i = self.group_idxs.index(group_idx)
        return self.hosts[i]

    def host_freq_count(self) -> dict[str, int]:
        freq: dict[str, int] = {}
        for h in self.hosts:
            freq[h] = freq.get(h, 0) + 1
        return freq

    def topology(self):
        """The placement's Topology (mpi/topology.py): group idx (the
        MPI rank of gang-scheduled worlds) → host → leader/local rank.
        The SAME object MpiWorld composes its hierarchical collectives
        over — the scheduler reads it for locality scoring and the
        planner exports it (get_cluster_topology)."""
        from faabric_tpu.mpi.topology import Topology

        return Topology.from_decision(self)

    # ------------------------------------------------------------------
    def to_dict(self) -> dict[str, Any]:
        # Hand-rolled (parallel-vector copies): dataclasses.asdict
        # deep-copies recursively and this rides every CALL_BATCH
        # response and planner journal app_update
        return {
            "app_id": self.app_id,
            "group_id": self.group_id,
            "hosts": list(self.hosts),
            "message_ids": list(self.message_ids),
            "app_idxs": list(self.app_idxs),
            "group_idxs": list(self.group_idxs),
            "mpi_ports": list(self.mpi_ports),
            "device_ids": list(self.device_ids),
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "SchedulingDecision":
        out = cls(app_id=d.get("app_id", 0), group_id=d.get("group_id", 0))
        out.hosts = list(d.get("hosts", []))
        out.message_ids = list(d.get("message_ids", []))
        out.app_idxs = list(d.get("app_idxs", []))
        out.group_idxs = list(d.get("group_idxs", []))
        out.mpi_ports = list(d.get("mpi_ports", []))
        out.device_ids = list(d.get("device_ids", []))
        return out

    @classmethod
    def from_point_to_point_mappings(cls, mappings: "Any") -> "SchedulingDecision":
        """Rebuild a decision from distributed PTP mappings (reference
        SchedulingDecision::fromPointToPointMappings)."""
        out = cls(app_id=mappings.app_id, group_id=mappings.group_id)
        for m in mappings.mappings:
            out.add_message(m.host, m.message_id, m.app_idx, m.group_idx,
                            m.mpi_port,
                            m.device_ids[0] if m.device_ids else -1)
        return out


def do_not_migrate_decision() -> SchedulingDecision:
    return SchedulingDecision(DO_NOT_MIGRATE, DO_NOT_MIGRATE)


def not_enough_slots_decision() -> SchedulingDecision:
    return SchedulingDecision(NOT_ENOUGH_SLOTS, NOT_ENOUGH_SLOTS)


def must_freeze_decision() -> SchedulingDecision:
    return SchedulingDecision(MUST_FREEZE, MUST_FREEZE)


def is_sentinel_decision(decision: SchedulingDecision) -> bool:
    return decision.app_id in (DO_NOT_MIGRATE, NOT_ENOUGH_SLOTS, MUST_FREEZE)
