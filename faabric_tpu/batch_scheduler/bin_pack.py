"""Bin-pack policy (reference src/batch-scheduler/BinPackScheduler.cpp).

NEW: fill hosts in decreasing order of free capacity. SCALE_CHANGE: co-locate
with the app's existing placement first. DIST_CHANGE: re-schedule from
scratch (app's slots virtually freed) and migrate only if the placement
spans fewer hosts or cuts cross-host links.
"""

from __future__ import annotations

from faabric_tpu.batch_scheduler.decision import SchedulingDecision
from faabric_tpu.batch_scheduler.scheduler import (
    BatchScheduler,
    DecisionType,
    HostMap,
    HostState,
    InFlightReqs,
)
from faabric_tpu.proto import BatchExecuteRequest


def sort_hosts_larger_first(hosts: list[HostState]) -> list[HostState]:
    # Free slots desc, total slots desc, ip desc
    # (reference BinPackScheduler.cpp isFirstHostLarger).
    return sorted(hosts, key=lambda h: (h.available, h.slots, h.ip), reverse=True)


def sort_hosts_by_app_freq(hosts: list[HostState],
                           freq: dict[str, int]) -> list[HostState]:
    # App placement count desc first, then the NEW criteria
    # (reference isFirstHostLargerWithFreq).
    return sorted(
        hosts,
        key=lambda h: (freq.get(h.ip, 0), h.available, h.slots, h.ip),
        reverse=True,
    )


def locality_score(decision: SchedulingDecision) -> tuple[int, int]:
    """(number of hosts, cross-host links in the fully-connected rank graph)
    — reference BinPackScheduler.cpp:97-148. On TPU the cross-host links are
    the collective hops that leave the ICI domain and ride DCN, which is why
    fewer is strictly better."""
    freq = decision.host_freq_count()
    if len(freq) <= 1:
        return (len(freq), 0)
    total = sum(freq.values())
    # Each message has an edge to every message on a different host; halve
    # the double count.
    cross = sum(n * (total - n) for n in freq.values()) // 2
    return (len(freq), cross)


class BinPackScheduler(BatchScheduler):
    def get_sorted_hosts(self, host_map: HostMap, in_flight: InFlightReqs,
                         req: BatchExecuteRequest,
                         decision_type: DecisionType) -> list[HostState]:
        hosts = list(host_map.values())
        if decision_type == DecisionType.NEW:
            return sort_hosts_larger_first(hosts)

        old_decision = in_flight[req.app_id][1]
        freq = old_decision.host_freq_count()

        if decision_type == DecisionType.SCALE_CHANGE:
            return sort_hosts_by_app_freq(hosts, freq)

        # DIST_CHANGE: give the app a fresh shot — free its current slots,
        # then sort by free capacity, breaking ties toward hosts already
        # running the app (minimises migrations on a tie).
        for h in hosts:
            if h.ip in freq:
                h.free(freq[h.ip])
        return sorted(
            hosts,
            key=lambda h: (h.available, freq.get(h.ip, 0), h.slots, h.ip),
            reverse=True,
        )

    def is_first_decision_better(self, host_map: HostMap,
                                 decision_a: SchedulingDecision,
                                 decision_b: SchedulingDecision) -> bool:
        # Fewer hosts wins; tie broken by fewer cross-host links.
        return locality_score(decision_a) < locality_score(decision_b)
