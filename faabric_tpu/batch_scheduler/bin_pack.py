"""Bin-pack policy (reference src/batch-scheduler/BinPackScheduler.cpp).

NEW: fill hosts in decreasing order of free capacity — except MPI
batches, which gang-schedule (ISSUE 9): the sort consults the world's
prospective Topology and prefers FILLING one host with the world's
ranks before spilling, so the ranks land co-located and the
hierarchical collectives get their shm tier. SCALE_CHANGE: co-locate
with the app's existing placement first. DIST_CHANGE: re-schedule from
scratch (app's slots virtually freed) and migrate only if the placement
spans fewer hosts or cuts cross-host links.
"""

from __future__ import annotations

from faabric_tpu.batch_scheduler.decision import SchedulingDecision
from faabric_tpu.batch_scheduler.scheduler import (
    BatchScheduler,
    DecisionType,
    HostMap,
    HostState,
    InFlightReqs,
)
from faabric_tpu.proto import BatchExecuteRequest


def sort_hosts_larger_first(hosts: list[HostState]) -> list[HostState]:
    # Free slots desc, total slots desc, ip desc
    # (reference BinPackScheduler.cpp isFirstHostLarger).
    return sorted(hosts, key=lambda h: (h.available, h.slots, h.ip), reverse=True)


def sort_hosts_gang(hosts: list[HostState], world_size: int,
                    prefer_devices: bool = False) -> list[HostState]:
    """Gang order for an MPI world of ``world_size`` ranks: the host
    that can swallow the most of the REMAINDER first; among hosts that
    fit the whole remainder, the tightest fit wins (an 8-rank world
    lands on the 8-free host, keeping the 16-free host whole for a
    bigger world). Greedy simulation rather than a one-shot key sort:
    after the first host spills, the remainder shrinks, and the
    tightest-fit rule must apply to THAT (hosts 6/5/4 free, world of
    10 → 6-host then the exact-fit 4-host, not the 5-host it would
    fragment). Hosts the world never reaches follow in the classic
    larger-first order. Capacity-blind larger-first would fragment the
    big host and scatter the next world topology-blind.

    ``prefer_devices`` (ISSUE 10; default OFF — the caller derives it
    from the REQUEST via ``request_wants_devices``, never from the host
    pool, so a world with no device demand cannot be steered onto chip
    hosts and starve a later device-eligible world of them) adds a
    mesh-contiguity tie-break: among hosts swallowing the same share of
    the remainder, one whose device count covers the ranks it would
    take ranks first — each rank gets its own chip, so the placement's
    Topology reads mesh_contiguous and the world's device-plane
    activation resolves cleanly instead of aliasing chips."""
    pool = list(hosts)
    order: list[HostState] = []
    remaining = world_size
    while pool and remaining > 0:
        def key(h, _rem=remaining):
            take = min(h.available, _rem)
            covers = 1 if (prefer_devices and take > 0
                           and h.n_devices >= take) else 0
            return (take, covers, -h.available, h.ip)

        best = max(pool, key=key)
        pool.remove(best)
        order.append(best)
        remaining -= best.available
    order.extend(sort_hosts_larger_first(pool))
    return order


def sort_hosts_by_app_freq(hosts: list[HostState],
                           freq: dict[str, int]) -> list[HostState]:
    # App placement count desc first, then the NEW criteria
    # (reference isFirstHostLargerWithFreq).
    return sorted(
        hosts,
        key=lambda h: (freq.get(h.ip, 0), h.available, h.slots, h.ip),
        reverse=True,
    )


def locality_score(decision: SchedulingDecision) -> tuple[int, int]:
    """(number of hosts, cross-host links in the fully-connected rank
    graph) — reference BinPackScheduler.cpp:97-148, read from the
    placement's Topology (the same object the MPI collectives compose
    over). On TPU the cross-host links are the collective hops that
    leave the ICI domain and ride DCN, which is why fewer is strictly
    better."""
    topo = decision.topology()
    return (topo.n_hosts, topo.cross_host_pairs())


def is_mpi_request(req: BatchExecuteRequest) -> bool:
    return req.n_messages() > 0 and bool(req.messages[0].is_mpi)


def request_wants_devices(req: BatchExecuteRequest) -> bool:
    """Device eligibility of a REQUEST (ISSUE 10): does this batch want
    each rank on its own chip? Today every gang-scheduled MPI world is
    device-eligible — the planner claims one device per rank
    unconditionally and the world may run the activation handshake —
    so this is exactly ``is_mpi_request``. One place to refine when the
    proto grows an explicit per-request device demand."""
    return is_mpi_request(req)


class BinPackScheduler(BatchScheduler):
    def get_sorted_hosts(self, host_map: HostMap, in_flight: InFlightReqs,
                         req: BatchExecuteRequest,
                         decision_type: DecisionType) -> list[HostState]:
        from faabric_tpu.util.config import get_system_config

        hosts = list(host_map.values())
        if decision_type == DecisionType.NEW:
            if (is_mpi_request(req)
                    and get_system_config().gang_schedule_mpi):
                return sort_hosts_gang(
                    hosts, req.n_messages(),
                    prefer_devices=request_wants_devices(req))
            return sort_hosts_larger_first(hosts)

        old_decision = in_flight[req.app_id][1]
        freq = old_decision.host_freq_count()

        if decision_type == DecisionType.SCALE_CHANGE:
            return sort_hosts_by_app_freq(hosts, freq)

        # DIST_CHANGE: give the app a fresh shot — free its current slots,
        # then sort by free capacity, breaking ties toward hosts already
        # running the app (minimises migrations on a tie).
        for h in hosts:
            if h.ip in freq:
                h.free(freq[h.ip])
        return sorted(
            hosts,
            key=lambda h: (h.available, freq.get(h.ip, 0), h.slots, h.ip),
            reverse=True,
        )

    def is_first_decision_better(self, host_map: HostMap,
                                 decision_a: SchedulingDecision,
                                 decision_b: SchedulingDecision) -> bool:
        # Fewer hosts wins; tie broken by fewer cross-host links.
        return locality_score(decision_a) < locality_score(decision_b)
