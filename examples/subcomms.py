"""Hierarchical collectives with sub-communicators.

Two logical hosts × 2 ranks: split by shared host
(MPI_COMM_TYPE_SHARED), reduce within each host, then let the host
leaders combine over a leaders-only communicator — the classic two-level
reduction pattern, coordination-free (no planner involvement in comm
creation).

Run: python examples/subcomms.py
"""

import os
import random
import sys
import threading

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from faabric_tpu.batch_scheduler.decision import SchedulingDecision
from faabric_tpu.mpi import MpiOp, MpiWorld
from faabric_tpu.transport.common import (
    clear_host_aliases,
    register_host_alias,
)
from faabric_tpu.transport.point_to_point import PointToPointBroker
from faabric_tpu.transport.ptp_remote import PointToPointServer

GROUP = 4040
_print_lock = threading.Lock()


def say(msg: str) -> None:
    with _print_lock:
        print(msg, flush=True)


def main() -> None:
    base = random.randint(20, 120) * 100
    register_host_alias("hA", "127.0.0.1", base)
    register_host_alias("hB", "127.0.0.1", base + 1000)
    brokers = {h: PointToPointBroker(h) for h in ("hA", "hB")}
    servers = [PointToPointServer(b) for b in brokers.values()]
    for s in servers:
        s.start()
    d = SchedulingDecision(app_id=GROUP, group_id=GROUP)
    for r in range(4):
        d.add_message("hA" if r < 2 else "hB", 100 + r, r, r)
    for b in brokers.values():
        b.set_up_local_mappings_from_decision(d)
    worlds = {h: MpiWorld(b, GROUP, 4, GROUP) for h, b in brokers.items()}

    def rank_fn(rank):
        world = worlds["hA" if rank < 2 else "hB"]
        world.refresh_rank_hosts()

        # Level 1: per-host communicator (shared-memory ranks)
        host_comm, host_rank = world.split_type_shared(rank)
        local = host_comm.allreduce(host_rank,
                                    np.array([rank + 1], np.int64),
                                    MpiOp.SUM)

        # Level 2: host leaders only
        leaders = [0, 2]
        leader_comm, lr = world.create_group_comm(rank, leaders)
        if leader_comm is not None:
            total = leader_comm.allreduce(lr, local, MpiOp.SUM)
            say(f"rank {rank}: host sum {int(local[0])}, "
                f"global {int(total[0])}")
        else:
            say(f"rank {rank}: host sum {int(local[0])}")
        world.barrier(rank)

    try:
        ts = [threading.Thread(target=rank_fn, args=(r,)) for r in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
    finally:
        for s in servers:
            s.stop()
        for b in brokers.values():
            b.clear()
        clear_host_aliases()


if __name__ == "__main__":
    main()
