"""A gang-scheduled MPI program through the full runtime stack.

One process hosts a planner and a worker; a registered guest function is
invoked once, creates a 4-rank MPI world (the planner gang-schedules the
other ranks, pinning each to a chip), and the ranks allreduce.

Run: python examples/gang_mpi.py
"""

import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from faabric_tpu.executor import (
    JaxExecutorFactory,
    clear_registered_functions,
    register_function,
)
from faabric_tpu.mpi import MpiOp
from faabric_tpu.planner import PlannerServer, get_planner
from faabric_tpu.proto import ReturnValue, batch_exec_factory
from faabric_tpu.runner import WorkerRuntime
from faabric_tpu.transport.common import (
    clear_host_aliases,
    register_host_alias,
)


@register_function("example", "allreduce")
def allreduce(ctx):
    world = ctx.mpi_world()
    rank = ctx.message.mpi_rank
    out = world.allreduce(rank, np.full(1024, rank + 1, np.int64),
                          MpiOp.SUM)
    return f"rank {rank} on chip {ctx.device_id}: sum={int(out[0])}".encode()


def main() -> None:
    base = random.randint(20, 120) * 100
    register_host_alias("planner", "127.0.0.1", base)
    register_host_alias("worker", "127.0.0.1", base + 1000)

    get_planner().reset()
    planner_server = PlannerServer(port_offset=base)
    planner_server.start()
    worker = WorkerRuntime(host="worker", slots=4, n_devices=4,
                           factory=JaxExecutorFactory(),
                           planner_host="planner")
    try:
        worker.start()
        req = batch_exec_factory("example", "allreduce", 1)
        req.messages[0].mpi_rank = 0
        req.messages[0].mpi_world_size = 4
        worker.planner_client.call_functions(req)
        r = worker.planner_client.get_message_result(
            req.app_id, req.messages[0].id, timeout=30.0)
        assert r.return_value == int(ReturnValue.SUCCESS), r.output_data
        print(r.output_data.decode())  # rank 0's view
        # Other ranks' results land asynchronously: poll until finished
        import time

        deadline = time.time() + 20
        status = worker.planner_client.get_batch_results(req.app_id)
        while not status.finished and time.time() < deadline:
            time.sleep(0.2)
            status = worker.planner_client.get_batch_results(req.app_id)
        for m in sorted(status.message_results, key=lambda m: m.mpi_rank):
            print(m.output_data.decode())
    finally:
        worker.shutdown()
        planner_server.stop()
        get_planner().reset()
        clear_host_aliases()
        clear_registered_functions()


if __name__ == "__main__":
    main()
