"""Data-parallel training on a device mesh — the 60-second tour.

Run (CPU mesh): JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/train_ddp.py

On TPU hardware drop the env vars; the same code lays the mesh over the
real chips and the Pallas kernels engage automatically
(attention_impl="auto").
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from faabric_tpu.util.device_env import force_cpu_if_requested

force_cpu_if_requested()

import jax
import numpy as np

from faabric_tpu.models import (
    ModelConfig,
    init_train_state,
    make_optimizer,
    make_train_step,
)
from faabric_tpu.parallel import MeshConfig, build_mesh


def main() -> None:
    devices = jax.devices()
    n = len(devices)
    tp = 2 if n % 2 == 0 else 1
    mesh = build_mesh(devices, MeshConfig(tp=tp))
    print(f"mesh: {dict(mesh.shape)} over {n} {devices[0].platform} device(s)")

    cfg = ModelConfig(vocab_size=512, d_model=128, n_layers=2, n_heads=8,
                      d_ff=256, max_seq=128)
    opt = make_optimizer(lr=1e-3)
    params, opt_state = init_train_state(jax.random.PRNGKey(0), cfg, mesh,
                                         opt)
    step = make_train_step(cfg, mesh, opt)

    # Input pipeline: deterministic shuffled windows, prefetched onto the
    # mesh one batch ahead (swap the array for TokenDataset.from_file to
    # stream a memmap'd corpus)
    from faabric_tpu.data import DataLoader, TokenDataset

    rng = np.random.RandomState(0)
    corpus = rng.randint(0, cfg.vocab_size, 50_000, dtype=np.int32)
    dp = mesh.shape["dp"]
    loader = DataLoader(TokenDataset(corpus, seq_len=64),
                        batch_size=dp * 4, mesh=mesh, seed=0)

    for i, (tokens, targets) in enumerate(loader):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        print(f"step {i}: loss {float(loss):.4f}")
        if i == 4:
            break


if __name__ == "__main__":
    main()
