"""Shared state between processes with the file authority — no servers.

Run: python examples/state_kv.py
(spawns a child process that reads and mutates the same key)
"""

import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

CHILD = """
import os, sys
sys.path.insert(0, {root!r})
os.environ["STATE_MODE"] = "file"
os.environ["STATE_DIR"] = {state_dir!r}
from faabric_tpu.state.state import State
kv = State("child").get_kv("example", "shared")
print("child sees:", kv.get_chunk(0, 5).decode())
kv.set_chunk(5, b"world")
kv.push_partial()
kv.append(b"child-was-here")
"""


def main() -> None:
    state_dir = tempfile.mkdtemp(prefix="faabric_state_")
    os.environ["STATE_MODE"] = "file"
    os.environ["STATE_DIR"] = state_dir
    from faabric_tpu.util.config import get_system_config

    get_system_config().reset()
    from faabric_tpu.state.state import State

    kv = State("parent").get_kv("example", "shared", 16)
    kv.set_chunk(0, b"hello")
    kv.push_partial()

    root = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    code = CHILD.format(root=os.path.abspath(root), state_dir=state_dir)
    out = subprocess.run([sys.executable, "-c", code],
                        capture_output=True, text=True, timeout=60)
    if out.returncode != 0:
        sys.exit(f"child failed:\n{out.stderr}")
    print(out.stdout.strip())

    kv.pull()
    print("parent sees:", kv.get_chunk(0, 10).decode())
    print("append log :", kv.get_appended(1)[0].decode())
    import shutil

    shutil.rmtree(state_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
