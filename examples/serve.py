"""Sampling generation with the KV cache (greedy, temperature, nucleus).

Run (CPU): JAX_PLATFORMS=cpu python examples/serve.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from faabric_tpu.util.device_env import force_cpu_if_requested

force_cpu_if_requested()

import jax
import jax.numpy as jnp
import numpy as np

from faabric_tpu.models import ModelConfig, init_params
from faabric_tpu.models.generate import generate


def main() -> None:
    cfg = ModelConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                      d_ff=128, max_seq=128, compute_dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, 256, (1, 16)), jnp.int32)

    greedy = generate(params, prompt, cfg, 16)
    print("greedy :", np.asarray(greedy)[0].tolist())

    # Varying temperature/top_p reuses ONE compiled decode program
    for t in (0.7, 1.0, 1.3):
        toks = generate(params, prompt, cfg, 16, jax.random.PRNGKey(1),
                        temperature=t, top_k=40, top_p=0.95,
                        prefill_chunk=8)
        print(f"t={t:<4}:", np.asarray(toks)[0].tolist())


if __name__ == "__main__":
    main()
